"""Device-mesh construction: the single mechanism for all parallelism.

The reference expresses parallelism operationally — torchrun + NCCL DDP
(``harness/determined/launch/torch_distributed.py``), Horovod
(``launch/horovod.py``), DeepSpeed ZeRO/pipeline (``pytorch/deepspeed/``),
and an interface-level ``ModelParallelUnit`` (``deepspeed/_mpu.py:9-50``).
On TPU all of those collapse into ONE abstraction: a ``jax.sharding.Mesh``
whose named axes carry data (dp), fully-sharded-data (fsdp), tensor (tp),
sequence/context (sp), expert (ep), and pipeline (pp) parallelism.  XLA
inserts the collectives (psum / all_gather / reduce_scatter / ppermute)
over ICI within a slice and DCN across slices.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


class MeshAxes:
    """Canonical mesh-axis names used across the framework."""

    DCN = "dcn"          # cross-slice data parallelism (slow DCN links)
    DATA = "data"        # pure data parallelism (gradients psum'd)
    FSDP = "fsdp"        # data parallelism with sharded params/opt-state
    TENSOR = "tensor"    # tensor (megatron-style) parallelism
    SEQUENCE = "seq"     # sequence / context parallelism (ring attention)
    EXPERT = "expert"    # MoE expert parallelism
    PIPELINE = "pipe"    # pipeline stages

    ALL = (DCN, DATA, FSDP, TENSOR, SEQUENCE, EXPERT, PIPELINE)
    # Axes over which a batch is split (used to compute per-shard batch).
    BATCH_AXES = (DCN, DATA, FSDP)
    # Batch axes that stay within one slice (ICI-reachable); the hierarchical
    # gradient sync reduce-scatters over these and crosses `dcn` with only
    # the resulting 1/N_ici fragment.
    ICI_BATCH_AXES = (DATA, FSDP)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative parallelism topology for one trial.

    This is the TPU analog of the reference's ``slots_per_trial`` plus the
    launcher choice: instead of "8 slots + torch_distributed launcher" a
    trial says ``MeshConfig(data=2, fsdp=2, tensor=2)``.

    A size of -1 for exactly one axis means "absorb all remaining devices".

    ``num_slices`` > 1 adds an outer ``dcn`` mesh axis spanning TPU slices:
    the batch additionally splits across slices, and the hierarchical
    gradient sync (``optimizations.hierarchical_collectives``) keeps the
    heavy reductions on ICI, crossing DCN with only sharded fragments.
    """

    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    expert: int = 1
    pipe: int = 1
    num_slices: int = 1

    def sizes(self) -> Tuple[int, ...]:
        """Per-slice (ICI) axis sizes; ``num_slices`` multiplies on top."""
        return (self.data, self.fsdp, self.tensor, self.seq, self.expert, self.pipe)

    @property
    def num_devices(self) -> int:
        n = max(1, self.num_slices)
        for s in self.sizes():
            if s != -1:
                n *= s
        return n

    def resolve(self, total_devices: int) -> "MeshConfig":
        """Fill in a single -1 axis from the total device count."""
        if self.num_slices < 1:
            raise ValueError("num_slices must be >= 1 (it is never a wildcard)")
        sizes = list(self.sizes())
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if wild:
            fixed = self.num_slices * math.prod(s for s in sizes if s != -1)
            if total_devices % fixed:
                raise ValueError(
                    f"{total_devices} devices not divisible by fixed axes {fixed}"
                )
            sizes[wild[0]] = total_devices // fixed
        resolved = MeshConfig(*sizes, num_slices=self.num_slices)
        if resolved.num_devices != total_devices:
            raise ValueError(
                f"mesh {resolved.sizes()} x {resolved.num_slices} slice(s) needs "
                f"{resolved.num_devices} devices, got {total_devices}"
            )
        return resolved

    @classmethod
    def data_parallel(cls, n: int = -1) -> "MeshConfig":
        return cls(data=n)

    @classmethod
    def fsdp_parallel(cls, n: int = -1) -> "MeshConfig":
        return cls(fsdp=n)


def _mesh_device_array(devices: Sequence[jax.Device], shape: Tuple[int, ...]) -> np.ndarray:
    """Arrange devices for the mesh.

    Axis order is chosen so the fastest-varying (innermost) axes are the
    ones with the heaviest communication (tensor, then sequence), which maps
    them onto the tightest ICI neighborhoods in the default device order —
    the analog of NCCL ring placement in the reference's DDP launcher.
    """
    if len(devices) < math.prod(shape):
        raise ValueError(f"need {math.prod(shape)} devices, have {len(devices)}")
    devs = np.asarray(devices[: math.prod(shape)], dtype=object)
    return devs.reshape(shape)


def _slice_major_order(
    devices: Sequence[jax.Device], num_slices: int, per_slice: int
) -> list:
    """Order devices slice-major so the outer ``dcn`` axis maps to slices.

    Real multislice TPU devices carry a ``slice_index`` attribute; group by
    it so every chip along the dcn axis really sits behind a DCN link.  On
    CPU (no slice_index) contiguous equal blocks of the default order
    emulate virtual slices — good enough for numerics/HLO tests, exactly
    like ``make_virtual_mesh`` emulates a multi-chip slice.
    """
    by_slice: dict = {}
    for d in devices:
        idx = getattr(d, "slice_index", None)
        if idx is None:
            by_slice = {}
            break
        by_slice.setdefault(idx, []).append(d)
    if len(by_slice) >= num_slices:
        chosen = sorted(by_slice)[:num_slices]
        if all(len(by_slice[s]) >= per_slice for s in chosen):
            out: list = []
            for s in chosen:
                out.extend(by_slice[s][:per_slice])
            return out
        raise ValueError(
            f"mesh wants {per_slice} devices per slice x {num_slices} slices, "
            f"but slice sizes are { {s: len(v) for s, v in by_slice.items()} }"
        )
    # virtual-slice emulation: contiguous blocks
    return list(devices[: num_slices * per_slice])


def make_mesh(
    config: MeshConfig,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` from a MeshConfig.

    Mesh axis order: (dcn, pipe, data, fsdp, expert, seq, tensor) —
    outermost axes communicate least (cross-slice DCN hops, pipeline p2p,
    DP gradient psum once per step), innermost communicate most (TP
    collectives inside every layer), so the innermost axes land on
    contiguous ICI neighbors.  ``dcn`` is always present (size 1 on a
    single slice); size-1 axes are dropped by the sharding rules, so
    single-slice behavior is unchanged.
    """
    devices = list(devices if devices is not None else jax.devices())
    config = config.resolve(len(devices)) if -1 in config.sizes() else config
    if config.num_devices > len(devices):
        raise ValueError(
            f"MeshConfig wants {config.num_devices} devices, only {len(devices)} present"
        )
    num_slices = max(1, config.num_slices)
    per_slice = config.num_devices // num_slices
    if num_slices > 1:
        devices = _slice_major_order(devices, num_slices, per_slice)
    shape = (
        num_slices,
        config.pipe,
        config.data,
        config.fsdp,
        config.expert,
        config.seq,
        config.tensor,
    )
    axis_names = (
        MeshAxes.DCN,
        MeshAxes.PIPELINE,
        MeshAxes.DATA,
        MeshAxes.FSDP,
        MeshAxes.EXPERT,
        MeshAxes.SEQUENCE,
        MeshAxes.TENSOR,
    )
    return Mesh(_mesh_device_array(devices, shape), axis_names)


def make_virtual_mesh(n: int, config: Optional[MeshConfig] = None) -> Mesh:
    """Mesh over the first ``n`` visible devices (driver dry-run path).

    Under ``--xla_force_host_platform_device_count=N`` this builds the
    multi-chip mesh on CPU so shardings compile without TPU hardware — the
    analog of the reference's artificial agent slots
    (``agent/internal/detect/detect.go:40-57``).
    """
    config = config or MeshConfig(data=-1)
    return make_mesh(config, jax.devices()[:n])


def local_mesh_devices(mesh: Mesh) -> list:
    """Devices of this mesh addressable by the current process."""
    local = set(d.id for d in jax.local_devices())
    return [d for d in mesh.devices.flat if d.id in local]
