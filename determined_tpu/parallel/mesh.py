"""Device-mesh construction: the single mechanism for all parallelism.

The reference expresses parallelism operationally — torchrun + NCCL DDP
(``harness/determined/launch/torch_distributed.py``), Horovod
(``launch/horovod.py``), DeepSpeed ZeRO/pipeline (``pytorch/deepspeed/``),
and an interface-level ``ModelParallelUnit`` (``deepspeed/_mpu.py:9-50``).
On TPU all of those collapse into ONE abstraction: a ``jax.sharding.Mesh``
whose named axes carry data (dp), fully-sharded-data (fsdp), tensor (tp),
sequence/context (sp), expert (ep), and pipeline (pp) parallelism.  XLA
inserts the collectives (psum / all_gather / reduce_scatter / ppermute)
over ICI within a slice and DCN across slices.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


class MeshAxes:
    """Canonical mesh-axis names used across the framework."""

    DATA = "data"        # pure data parallelism (gradients psum'd)
    FSDP = "fsdp"        # data parallelism with sharded params/opt-state
    TENSOR = "tensor"    # tensor (megatron-style) parallelism
    SEQUENCE = "seq"     # sequence / context parallelism (ring attention)
    EXPERT = "expert"    # MoE expert parallelism
    PIPELINE = "pipe"    # pipeline stages

    ALL = (DATA, FSDP, TENSOR, SEQUENCE, EXPERT, PIPELINE)
    # Axes over which a batch is split (used to compute per-shard batch).
    BATCH_AXES = (DATA, FSDP)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative parallelism topology for one trial.

    This is the TPU analog of the reference's ``slots_per_trial`` plus the
    launcher choice: instead of "8 slots + torch_distributed launcher" a
    trial says ``MeshConfig(data=2, fsdp=2, tensor=2)``.

    A size of -1 for exactly one axis means "absorb all remaining devices".
    """

    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    expert: int = 1
    pipe: int = 1

    def sizes(self) -> Tuple[int, ...]:
        return (self.data, self.fsdp, self.tensor, self.seq, self.expert, self.pipe)

    @property
    def num_devices(self) -> int:
        n = 1
        for s in self.sizes():
            if s != -1:
                n *= s
        return n

    def resolve(self, total_devices: int) -> "MeshConfig":
        """Fill in a single -1 axis from the total device count."""
        sizes = list(self.sizes())
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if wild:
            fixed = math.prod(s for s in sizes if s != -1)
            if total_devices % fixed:
                raise ValueError(
                    f"{total_devices} devices not divisible by fixed axes {fixed}"
                )
            sizes[wild[0]] = total_devices // fixed
        resolved = MeshConfig(*sizes)
        if resolved.num_devices != total_devices:
            raise ValueError(
                f"mesh {resolved.sizes()} needs {resolved.num_devices} devices, "
                f"got {total_devices}"
            )
        return resolved

    @classmethod
    def data_parallel(cls, n: int = -1) -> "MeshConfig":
        return cls(data=n)

    @classmethod
    def fsdp_parallel(cls, n: int = -1) -> "MeshConfig":
        return cls(fsdp=n)


def _mesh_device_array(devices: Sequence[jax.Device], shape: Tuple[int, ...]) -> np.ndarray:
    """Arrange devices for the mesh.

    Axis order is chosen so the fastest-varying (innermost) axes are the
    ones with the heaviest communication (tensor, then sequence), which maps
    them onto the tightest ICI neighborhoods in the default device order —
    the analog of NCCL ring placement in the reference's DDP launcher.
    """
    if len(devices) < math.prod(shape):
        raise ValueError(f"need {math.prod(shape)} devices, have {len(devices)}")
    devs = np.asarray(devices[: math.prod(shape)], dtype=object)
    return devs.reshape(shape)


def make_mesh(
    config: MeshConfig,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` from a MeshConfig.

    Mesh axis order: (pipe, data, fsdp, expert, seq, tensor) — outermost
    axes communicate least (pipeline p2p, DP gradient psum once per step),
    innermost communicate most (TP collectives inside every layer), so the
    innermost axes land on contiguous ICI neighbors.
    """
    devices = list(devices if devices is not None else jax.devices())
    config = config.resolve(len(devices)) if -1 in config.sizes() else config
    if config.num_devices > len(devices):
        raise ValueError(
            f"MeshConfig wants {config.num_devices} devices, only {len(devices)} present"
        )
    shape = (config.pipe, config.data, config.fsdp, config.expert, config.seq, config.tensor)
    axis_names = (
        MeshAxes.PIPELINE,
        MeshAxes.DATA,
        MeshAxes.FSDP,
        MeshAxes.EXPERT,
        MeshAxes.SEQUENCE,
        MeshAxes.TENSOR,
    )
    return Mesh(_mesh_device_array(devices, shape), axis_names)


def make_virtual_mesh(n: int, config: Optional[MeshConfig] = None) -> Mesh:
    """Mesh over the first ``n`` visible devices (driver dry-run path).

    Under ``--xla_force_host_platform_device_count=N`` this builds the
    multi-chip mesh on CPU so shardings compile without TPU hardware — the
    analog of the reference's artificial agent slots
    (``agent/internal/detect/detect.go:40-57``).
    """
    config = config or MeshConfig(data=-1)
    return make_mesh(config, jax.devices()[:n])


def local_mesh_devices(mesh: Mesh) -> list:
    """Devices of this mesh addressable by the current process."""
    local = set(d.id for d in jax.local_devices())
    return [d for d in mesh.devices.flat if d.id in local]
