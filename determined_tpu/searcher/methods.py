"""Basic search methods: single, random, grid.

Reference: ``master/pkg/searcher/{single,random,grid}.go`` semantics.
"""

from __future__ import annotations

from typing import Any, Dict, List

from determined_tpu.config.hyperparameters import grid_points
from determined_tpu.searcher._base import (
    Action,
    SearcherContext,
    SearchMethod,
    Shutdown,
)


class SingleSearch(SearchMethod):
    """One trial with directly-sampled hyperparameters."""

    def __init__(self) -> None:
        self._closed = 0

    def initial_trials(self, ctx: SearcherContext) -> List[Action]:
        return [ctx.create()]

    def validation_completed(self, ctx, request_id, metrics) -> List[Action]:
        return []

    def trial_exited(self, ctx, request_id) -> List[Action]:
        self._closed += 1
        return [Shutdown()]

    def progress(self, trial_progress, trials_closed) -> float:
        if self._closed:
            return 1.0
        return next(iter(trial_progress.values()), 0.0)

    def state_dict(self):
        return {"closed": self._closed}

    def load_state_dict(self, state):
        self._closed = state["closed"]


class RandomSearch(SearchMethod):
    """max_trials independently-sampled trials."""

    def __init__(self, max_trials: int, max_concurrent_trials: int = 16) -> None:
        self.max_trials = max_trials
        self.max_concurrent = max(1, min(max_concurrent_trials, max_trials))
        self._created = 0
        self._closed = 0

    def initial_trials(self, ctx: SearcherContext) -> List[Action]:
        n = min(self.max_concurrent, self.max_trials)
        actions = [ctx.create() for _ in range(n)]
        self._created += n
        return actions

    def validation_completed(self, ctx, request_id, metrics) -> List[Action]:
        return []

    def trial_exited(self, ctx, request_id) -> List[Action]:
        self._closed += 1
        if self._created < self.max_trials:
            self._created += 1
            return [ctx.create()]
        if self._closed >= self.max_trials:
            return [Shutdown()]
        return []

    def progress(self, trial_progress, trials_closed) -> float:
        done = self._closed + sum(trial_progress.values())
        return min(1.0, done / self.max_trials)

    def state_dict(self):
        return {"created": self._created, "closed": self._closed}

    def load_state_dict(self, state):
        self._created, self._closed = state["created"], state["closed"]


class GridSearch(SearchMethod):
    """Cartesian expansion of the hp space (reference ``grid.go``)."""

    def __init__(self, hparams: Dict[str, Any], max_concurrent_trials: int = 16) -> None:
        self.points = grid_points(hparams)
        self.max_concurrent = max(1, max_concurrent_trials)
        self._next_point = 0
        self._closed = 0

    def _create_next(self, ctx: SearcherContext) -> List[Action]:
        if self._next_point >= len(self.points):
            return []
        p = self.points[self._next_point]
        self._next_point += 1
        return [ctx.create(p)]

    def initial_trials(self, ctx: SearcherContext) -> List[Action]:
        out: List[Action] = []
        for _ in range(min(self.max_concurrent, len(self.points))):
            out.extend(self._create_next(ctx))
        return out

    def validation_completed(self, ctx, request_id, metrics) -> List[Action]:
        return []

    def trial_exited(self, ctx, request_id) -> List[Action]:
        self._closed += 1
        actions = self._create_next(ctx)
        if not actions and self._closed >= len(self.points):
            return [Shutdown()]
        return actions

    def progress(self, trial_progress, trials_closed) -> float:
        done = self._closed + sum(trial_progress.values())
        return min(1.0, done / max(len(self.points), 1))

    def state_dict(self):
        return {"next_point": self._next_point, "closed": self._closed}

    def load_state_dict(self, state):
        self._next_point, self._closed = state["next_point"], state["closed"]
