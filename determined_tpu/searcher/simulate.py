"""Trial-free searcher simulation: replay any method against a curve model.

Reference: ``master/pkg/searcher/simulate.go:65`` (`det preview-search`)
generalized into a harness that makes *method choice* testable: every
registered SearchMethod — including the clone-based PBT — runs against a
deterministic learning-curve model in milliseconds, and the report is a
best-metric-vs-budget table instead of a single end state.

Two model families:

- ``SyntheticCurveModel``: seeded lr-sensitive power-law curves.  A
  config's loss floor depends on how far its learning rate sits from a
  hidden optimum; loss decays toward that floor with *effective* training
  units.  Effective units include units inherited through PBT clones, so
  exploit/explore dynamics (children resume the parent's progress, then
  explore a better lr) are faithfully scored.
- ``JournalCurveModel``: recorded curves, lifted from a real experiment's
  journal (``trial_validated`` records).  A simulated trial follows the
  recorded trial whose hyperparameters are nearest in (log-scaled)
  numeric space, interpolated at its effective unit count.

All randomness is seeded; two runs with the same seed produce identical
reports — the property the mid-generation replay tests lean on.
"""

from __future__ import annotations

import dataclasses
import math
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from determined_tpu.config.experiment import (
    ExperimentConfig,
    Length,
    SearcherConfig,
)
from determined_tpu.searcher._base import RequestID
from determined_tpu.searcher._searcher import Searcher, method_from_config

DEFAULT_METHODS = ("random", "asha", "hyperband", "pbt")


def _numeric_hps(hparams: Dict[str, Any], prefix: str = "") -> Dict[str, float]:
    """Flatten numeric leaves, log-scaling the small-positive ones so a
    learning-rate distance is measured in decades, not absolute deltas."""
    out: Dict[str, float] = {}
    for k, v in (hparams or {}).items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_numeric_hps(v, key + "."))
        elif isinstance(v, bool):
            continue
        elif isinstance(v, (int, float)):
            fv = float(v)
            # the whole sub-1.0 range scales together: a perturb clamped to
            # an lr bound (e.g. exactly 0.1) must not jump coordinate
            # systems relative to its neighbors
            out[key] = math.log10(fv) if 0.0 < fv < 1.0 else fv
    return out


class SyntheticCurveModel:
    """Seeded power-law loss curves with an lr-shaped floor.

    ``metric(hparams, units)`` is a pure function of (seed, hparams,
    units): the per-config jitter comes from hashing the hparams with the
    seed, never from shared mutable rng state — so validation order cannot
    change a trial's curve.
    """

    def __init__(
        self,
        seed: int = 0,
        *,
        lr_key: str = "lr",
        lr_optimum: float = 10 ** -2.5,
        halflife: float = 16.0,
        noise: float = 0.05,
    ) -> None:
        self.seed = seed
        self.lr_key = lr_key
        self.lr_optimum = lr_optimum
        self.halflife = halflife
        self.noise = noise

    def _config_jitter(self, hparams: Dict[str, Any]) -> float:
        # stable across processes (Python's str hash is salted per run)
        items = repr((self.seed, sorted(_numeric_hps(hparams).items())))
        h = zlib.crc32(items.encode()) & 0xFFFFFFFF
        return (h / 0xFFFFFFFF - 0.5) * 2.0  # [-1, 1]

    def metric(self, hparams: Dict[str, Any], units: float) -> float:
        flat = _numeric_hps(hparams)
        lr_log = flat.get(self.lr_key)
        if lr_log is None:
            lr_log = next(iter(flat.values()), math.log10(self.lr_optimum))
        mis = (lr_log - math.log10(self.lr_optimum)) ** 2
        floor = 0.2 + 0.4 * mis
        jitter = self._config_jitter(hparams) * self.noise
        span = 2.0 * (1.0 + jitter)
        return floor + span * self.halflife / (self.halflife + max(units, 0.0))


class JournalCurveModel:
    """Curves recorded from a real experiment journal."""

    def __init__(self, curves: List[Tuple[Dict[str, float], List[Tuple[float, float]]]]):
        if not curves:
            raise ValueError("no recorded curves (journal had no validations)")
        self.curves = curves

    @classmethod
    def from_journal(cls, path: str, metric: str, time_metric: str = "batches"
                     ) -> "JournalCurveModel":
        from determined_tpu.experiment.journal import read_journal

        replay = read_journal(path)
        by_rid: Dict[int, List[Tuple[float, float]]] = {}
        for rec in replay.records:
            if rec.get("type") != "trial_validated":
                continue
            m = rec.get("metrics") or {}
            if not isinstance(m.get(metric), (int, float)):
                continue
            step = m.get(time_metric)
            if not isinstance(step, (int, float)):
                continue
            by_rid.setdefault(int(rec["rid"]), []).append((float(step), float(m[metric])))
        curves = []
        for rid, points in sorted(by_rid.items()):
            hp = _numeric_hps(replay.created.get(rid, {}))
            curves.append((hp, sorted(points)))
        return cls(curves)

    def metric(self, hparams: Dict[str, Any], units: float) -> float:
        flat = _numeric_hps(hparams)

        def dist(hp: Dict[str, float]) -> float:
            keys = set(flat) | set(hp)
            return sum((flat.get(k, 0.0) - hp.get(k, 0.0)) ** 2 for k in keys)

        _, points = min(self.curves, key=lambda c: dist(c[0]))
        xs = [p[0] for p in points]
        ys = [p[1] for p in points]
        return float(np.interp(units, xs, ys))


@dataclasses.dataclass
class SimulationReport:
    """What one simulated search did, digested for comparison."""

    method: str
    seed: int
    trials_created: int
    total_units: int
    max_time: int
    best_metric: Optional[float]
    best_trial: Optional[int]
    best_hparams: Optional[Dict[str, Any]]
    # (cumulative units spent, best metric so far) at every validation
    curve: List[Tuple[int, float]]
    trial_units: Dict[int, int]
    lineage: Dict[int, Optional[int]]

    def best_at(self, units: int) -> Optional[float]:
        """Best metric the method had found once ``units`` were spent."""
        best = None
        for spent, value in self.curve:
            if spent > units:
                break
            best = value
        return best


def _default_period(scfg: SearcherConfig, max_time: int) -> int:
    if scfg.name == "hyperband":
        # epsilon matches hyperband_brackets: exact powers of eta must not
        # round the deepest bracket away
        s_max = int(
            math.log(max(max_time, 2)) / math.log(max(scfg.divisor, 2)) + 1e-9
        )
        return max(int(max_time / scfg.divisor ** s_max), 1)
    if scfg.name == "pbt":
        return max(max_time // 4, 1)
    return max(int(max_time // (scfg.divisor ** (scfg.num_rungs - 1))), 1)


def simulate_method(
    config: ExperimentConfig,
    model: Any = None,
    *,
    seed: int = 0,
    report_period: int = 0,
) -> SimulationReport:
    """Run one whole search synchronously against a curve model.

    Round-robin execution: each pass, every running trial advances one
    validation period and reports; searcher decisions (stops, clones,
    shutdown) apply immediately.  Clone creates inherit the parent's
    effective unit count, so a PBT child's curve continues where its
    exploit parent left off — the simulator analog of the driver's
    checkpoint materialization.
    """
    scfg = config.searcher
    model = model or SyntheticCurveModel(seed)
    method = method_from_config(scfg, config.hyperparameters)
    searcher = Searcher(method, config.hyperparameters, seed)
    max_time = scfg.max_time or (scfg.max_length.units if scfg.max_length else 100)
    period = int(report_period or _default_period(scfg, max_time))

    better = (lambda a, b: a < b) if scfg.smaller_is_better else (lambda a, b: a > b)
    time_metric = scfg.time_metric or "batches"
    own_steps: Dict[RequestID, int] = {}
    inherited: Dict[RequestID, int] = {}
    lineage: Dict[RequestID, Optional[int]] = {}
    seen: set = set()
    curve: List[Tuple[int, float]] = []
    total_units = 0
    best: Optional[float] = None
    best_rid: Optional[int] = None

    def absorb_new_trials() -> None:
        for rid, rec in list(searcher.trials.items()):
            if rid in seen:
                continue
            seen.add(rid)
            src = rec.source_trial_id
            lineage[rid] = src
            inherited[rid] = (
                inherited.get(src, 0) + own_steps.get(src, 0) if src is not None else 0
            )

    searcher.start()
    absorb_new_trials()
    guard = 0
    while searcher.shutdown is None and guard < 100_000:
        guard += 1
        running = sorted(
            (t for t in searcher.trials.values() if t.running),
            key=lambda t: t.request_id,
        )
        if not running:
            break
        for rec in running:
            if searcher.shutdown is not None:
                break
            rid = rec.request_id
            step = own_steps.get(rid, 0) + period
            own_steps[rid] = step
            total_units += period
            value = model.metric(rec.hparams, inherited.get(rid, 0) + step)
            if best is None or better(value, best):
                best, best_rid = value, rid
            curve.append((total_units, best))
            searcher.on_validation(rid, {scfg.metric: value, time_metric: step})
            if rec.stopped_by_searcher or step >= max_time:
                searcher.on_trial_exited(rid)
            absorb_new_trials()
    return SimulationReport(
        method=scfg.name,
        seed=seed,
        trials_created=len(searcher.trials),
        total_units=total_units,
        max_time=max_time,
        best_metric=best,
        best_trial=best_rid,
        best_hparams=(
            searcher.trials[best_rid].hparams if best_rid is not None else None
        ),
        curve=curve,
        trial_units=dict(own_steps),
        lineage=lineage,
    )


def method_variant(config: ExperimentConfig, name: str) -> ExperimentConfig:
    """A copy of ``config`` running method ``name`` at (roughly) equal
    total budget: PBT splits the per-trial budget into generations so a
    surviving line trains ``max_time`` units total, like an un-stopped
    trial under every other method; hyperband sizes itself canonically.
    """
    scfg = config.searcher
    max_time = scfg.max_time or (scfg.max_length.units if scfg.max_length else 100)
    updates: Dict[str, Any] = {"name": name, "max_time": max_time, "max_length": None}
    if name == "pbt":
        gen_len = max(max_time // scfg.num_generations, 1)
        updates.update(
            max_time=gen_len,
            population_size=scfg.population_size or max(scfg.max_trials, 2),
        )
    new_scfg = dataclasses.replace(scfg, **updates)
    return dataclasses.replace(config, searcher=new_scfg)


def compare_methods(
    config: ExperimentConfig,
    methods: Sequence[str] = DEFAULT_METHODS,
    model: Any = None,
    *,
    seed: int = 0,
    report_period: int = 0,
) -> List[SimulationReport]:
    """Simulate several methods from one base config, same model + seed."""
    return [
        simulate_method(
            method_variant(config, name),
            model if model is not None else SyntheticCurveModel(seed),
            seed=seed,
            report_period=report_period,
        )
        for name in methods
    ]


def format_comparison(reports: List[SimulationReport]) -> str:
    """Deterministic best-metric-vs-budget table."""
    if not reports:
        return "(no methods simulated)"
    budget = max(r.total_units for r in reports)
    marks = [max(int(budget * f), 1) for f in (0.25, 0.5, 1.0)]
    header = (
        f"{'method':<14} {'trials':>6} {'units':>8} "
        + " ".join(f"{'best@' + _fmt_units(m):>12}" for m in marks)
        + f" {'best':>10}"
    )
    lines = [header, "-" * len(header)]
    for r in reports:
        cells = []
        for m in marks:
            v = r.best_at(m)
            cells.append(f"{v:>12.4f}" if v is not None else f"{'-':>12}")
        best = f"{r.best_metric:>10.4f}" if r.best_metric is not None else f"{'-':>10}"
        lines.append(
            f"{r.method:<14} {r.trials_created:>6} {r.total_units:>8} "
            + " ".join(cells)
            + f" {best}"
        )
    return "\n".join(lines)


def _fmt_units(units: int) -> str:
    if units >= 10_000:
        return f"{units / 1000:.0f}k"
    return str(units)
