"""Searcher driver: event dispatch, state tracking, simulation harness.

Reference: ``master/pkg/searcher/searcher.go:45,226`` (the stateful wrapper
the experiment engine talks to) and ``simulate.go:65`` (dry-run preview of
what a search method will do — used for tests and `det preview-search`).
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import Any, Callable, Dict, List, Optional

from determined_tpu.config.experiment import ExperimentConfig, SearcherConfig
from determined_tpu.searcher._base import (
    Action,
    Create,
    RequestID,
    SearcherContext,
    SearchMethod,
    Shutdown,
    Stop,
)
from determined_tpu.searcher.adaptive import make_adaptive_asha
from determined_tpu.searcher.asha import ASHASearch
from determined_tpu.searcher.methods import GridSearch, RandomSearch, SingleSearch
from determined_tpu.searcher._hyperband import HyperbandSearch
from determined_tpu.searcher._pbt import PBTSearch


def method_from_config(
    cfg: SearcherConfig, hparams: Dict[str, Any]
) -> SearchMethod:
    """Build the SearchMethod an experiment config asks for."""
    max_time = cfg.max_time
    if max_time is None and cfg.max_length is not None:
        max_time = cfg.max_length.units
    if cfg.name == "driver":
        from determined_tpu.config.experiment import InvalidExperimentConfig

        # the master-side stub for cluster-driven searches: the config the
        # master stores has its searcher REWRITTEN to this name; a driver
        # cannot reconstruct the original search method from it
        raise InvalidExperimentConfig(
            "searcher 'driver' is execution-only (the master-side stub for "
            "cluster experiments); run the search with the ORIGINAL config "
            "— the one holding the real method (asha/random/...) — not the "
            "rewritten config fetched from the master"
        )
    if cfg.name == "single":
        return SingleSearch()
    if cfg.name == "random":
        return RandomSearch(cfg.max_trials, cfg.max_concurrent_trials)
    if cfg.name == "grid":
        return GridSearch(hparams, cfg.max_concurrent_trials)
    if cfg.name == "asha":
        return ASHASearch(
            metric=cfg.metric,
            smaller_is_better=cfg.smaller_is_better,
            max_time=max_time or 100,
            time_metric=cfg.time_metric or "batches",
            num_rungs=cfg.num_rungs,
            divisor=cfg.divisor,
            max_trials=cfg.max_trials,
            max_concurrent_trials=cfg.max_concurrent_trials,
        )
    if cfg.name == "hyperband":
        return HyperbandSearch(
            metric=cfg.metric,
            smaller_is_better=cfg.smaller_is_better,
            max_time=max_time or 100,
            time_metric=cfg.time_metric or "batches",
            divisor=cfg.divisor,
            max_trials=cfg.max_trials,
            max_concurrent_trials=cfg.max_concurrent_trials,
        )
    if cfg.name == "pbt":
        return PBTSearch(
            metric=cfg.metric,
            smaller_is_better=cfg.smaller_is_better,
            population_size=cfg.population_size or max(cfg.max_trials, 2),
            num_generations=cfg.num_generations,
            truncate_fraction=cfg.truncate_fraction,
            perturb_factor=cfg.perturb_factor,
            resample_probability=cfg.resample_probability,
            time_metric=cfg.time_metric or "batches",
        )
    if cfg.name == "adaptive_asha":
        return make_adaptive_asha(
            metric=cfg.metric,
            smaller_is_better=cfg.smaller_is_better,
            max_time=max_time or 100,
            time_metric=cfg.time_metric or "batches",
            max_trials=cfg.max_trials,
            max_rungs=cfg.num_rungs,
            divisor=cfg.divisor,
            mode=cfg.mode,
            max_concurrent_trials=cfg.max_concurrent_trials,
            bracket_rungs=cfg.bracket_rungs,
        )
    raise ValueError(f"unknown searcher {cfg.name!r}")


@dataclasses.dataclass
class TrialRecord:
    request_id: RequestID
    hparams: Dict[str, Any]
    running: bool = True
    stopped_by_searcher: bool = False
    exited: bool = False
    metrics: Optional[Dict[str, Any]] = None  # last validation
    # clone provenance (PBT exploit): initial state comes from this trial's
    # newest usable checkpoint instead of a fresh init
    source_trial_id: Optional[RequestID] = None


class Searcher:
    """Stateful wrapper the experiment engine drives.

    Event entry points serialize on an internal lock: the concurrent trial
    scheduler fires ``on_validation``/``set_trial_progress`` from trial
    threads while the dispatcher thread drives exits and reads pending
    creates, and SearchMethod implementations are written single-threaded
    (rung lists, rng draws, id counters).
    """

    def __init__(
        self, method: SearchMethod, hparams: Dict[str, Any], seed: int = 0
    ) -> None:
        self.method = method
        self.ctx = SearcherContext(hparams, seed)
        self.trials: Dict[RequestID, TrialRecord] = {}
        self.shutdown: Optional[Shutdown] = None
        self._trial_progress: Dict[RequestID, float] = {}
        self._started = False
        # RLock: _absorb recurses through trial_created events
        self._lock = threading.RLock()

    # -- event entry points (called by the experiment engine) --------------

    def _absorb(self, actions: List[Action]) -> List[Action]:
        for a in actions:
            if isinstance(a, Create):
                self.trials[a.request_id] = TrialRecord(
                    a.request_id, a.hparams, source_trial_id=a.source_trial_id
                )
            elif isinstance(a, Stop):
                if a.request_id in self.trials:
                    self.trials[a.request_id].stopped_by_searcher = True
            elif isinstance(a, Shutdown):
                self.shutdown = a
        # trial_created events fire for newly absorbed creates
        extra: List[Action] = []
        for a in actions:
            if isinstance(a, Create):
                extra.extend(self.method.trial_created(self.ctx, a.request_id))
        if extra:
            actions = actions + self._absorb(extra)
        return actions

    def start(self) -> List[Action]:
        with self._lock:
            if self._started:
                # a restored (or restarted) searcher must not re-run
                # initial_trials: the creates it would emit already exist,
                # and the duplicate draws would burn request ids / rng state
                return []
            self._started = True
            return self._absorb(self.method.initial_trials(self.ctx))

    def on_validation(
        self, request_id: RequestID, metrics: Dict[str, Any]
    ) -> List[Action]:
        with self._lock:
            if request_id in self.trials:
                self.trials[request_id].metrics = dict(metrics)
            return self._absorb(
                self.method.validation_completed(self.ctx, request_id, metrics)
            )

    def on_trial_exited(self, request_id: RequestID) -> List[Action]:
        with self._lock:
            if request_id in self.trials:
                rec = self.trials[request_id]
                rec.running = False
                rec.exited = True
            return self._absorb(self.method.trial_exited(self.ctx, request_id))

    def on_trial_exited_early(self, request_id: RequestID, reason: str) -> List[Action]:
        with self._lock:
            if request_id in self.trials:
                self.trials[request_id].running = False
                self.trials[request_id].exited = True
            return self._absorb(
                self.method.trial_exited_early(self.ctx, request_id, reason)
            )

    def set_trial_progress(self, request_id: RequestID, progress: float) -> None:
        with self._lock:
            self._trial_progress[request_id] = progress

    def progress(self) -> float:
        with self._lock:
            closed = {rid: t.exited for rid, t in self.trials.items()}
            return self.method.progress(self._trial_progress, closed)

    # -- thread-safe views (the concurrent scheduler's read surface) -------

    def runnable_trials(self) -> List[TrialRecord]:
        """Snapshot of trials that are created and not yet exited."""
        with self._lock:
            return [t for t in self.trials.values() if t.running and not t.exited]

    def trial_records(self) -> List[TrialRecord]:
        """Locked snapshot of ALL trial records (e.g. for GC metric
        ranking); iterating ``self.trials`` directly races concurrent
        creates."""
        with self._lock:
            return list(self.trials.values())

    def is_stopped(self, request_id: RequestID) -> bool:
        """Whether the method has asked this trial to stop early."""
        with self._lock:
            rec = self.trials.get(request_id)
            return bool(rec is not None and rec.stopped_by_searcher)

    def clone_source_trials(self) -> List[RequestID]:
        """Trials whose latest checkpoints are live clone sources: the
        method's own candidates (PBT's current population) plus the named
        source of every trial that has not finished cloning from it yet.
        Checkpoint GC must keep these even when metric-ranked retention
        would rotate them out."""
        with self._lock:
            out = set(self.method.clone_source_trials())
            for rec in self.trials.values():
                if rec.source_trial_id is not None and not rec.exited:
                    out.add(rec.source_trial_id)
            return sorted(out)

    # -- snapshot ----------------------------------------------------------

    def state_json(self) -> str:
        with self._lock:
            return self._state_json_locked()

    def _state_json_locked(self) -> str:
        return json.dumps(
            {
                "method": self.method.state_dict(),
                "ctx": self.ctx.state_dict(),
                "started": self._started,
                "trials": {
                    str(rid): dataclasses.asdict(t) for rid, t in self.trials.items()
                },
                "trial_progress": {str(k): v for k, v in self._trial_progress.items()},
                "shutdown": (
                    None
                    if self.shutdown is None
                    else {"cancel": self.shutdown.cancel, "failure": self.shutdown.failure}
                ),
            }
        )

    def restore_json(self, text: str) -> None:
        with self._lock:
            self._restore_json_locked(text)

    def _restore_json_locked(self, text: str) -> None:
        state = json.loads(text)
        self.method.load_state_dict(state["method"])
        if "ctx" in state:
            self.ctx.load_state_dict(state["ctx"])
        # any snapshot implies the search had started (older snapshots
        # predate the flag)
        self._started = bool(state.get("started", True))
        self.trials = {
            int(rid): TrialRecord(**t) for rid, t in state["trials"].items()
        }
        self._trial_progress = {
            int(k): v for k, v in state.get("trial_progress", {}).items()
        }
        sd = state["shutdown"]
        if sd:
            self.shutdown = Shutdown(**sd) if isinstance(sd, dict) else Shutdown()


def simulate(
    config: ExperimentConfig,
    trial_fn: Callable[[Dict[str, Any], int], float],
    *,
    seed: int = 0,
    report_period: int = 0,
) -> Dict[str, Any]:
    """Run a whole search synchronously against a synthetic trial function.

    ``trial_fn(hparams, time_step) -> metric`` models a trial's validation
    metric at a given step.  Back-compat wrapper over the full harness in
    ``searcher/simulate.py`` (curve models, clone inheritance,
    best-vs-budget reports); clone-based methods see ``time_step`` as the
    trial's EFFECTIVE units including inherited progress.

    Reference: ``master/pkg/searcher/simulate.go:65``.
    """
    from determined_tpu.searcher.simulate import simulate_method

    class _FnModel:
        def metric(self, hparams: Dict[str, Any], units: float) -> float:
            return trial_fn(hparams, int(units))

    report = simulate_method(
        config, _FnModel(), seed=seed, report_period=report_period
    )
    return {
        "trials_created": report.trials_created,
        "total_units": report.total_units,
        "best_metric": report.best_metric,
        "max_time": report.max_time,
        "trial_units": dict(report.trial_units),
    }
