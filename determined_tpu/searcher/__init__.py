"""Hyperparameter search: single/random/grid/ASHA/adaptive-ASHA/Hyperband/PBT
+ a trial-free simulation harness (searcher/simulate.py)."""

from determined_tpu.searcher._base import (
    Action,
    Create,
    ExitedReason,
    RequestID,
    SearcherContext,
    SearchMethod,
    Shutdown,
    Stop,
)
from determined_tpu.searcher._searcher import (
    Searcher,
    TrialRecord,
    method_from_config,
    simulate,
)
from determined_tpu.searcher.adaptive import TournamentSearch, make_adaptive_asha
from determined_tpu.searcher.asha import ASHASearch
from determined_tpu.searcher.methods import GridSearch, RandomSearch, SingleSearch
from determined_tpu.searcher._hyperband import Bracket, HyperbandSearch, hyperband_brackets
from determined_tpu.searcher._pbt import PBTSearch, perturb_hparams
from determined_tpu.searcher.simulate import (
    JournalCurveModel,
    SimulationReport,
    SyntheticCurveModel,
    compare_methods,
    format_comparison,
    simulate_method,
)
# importing the simulate SUBMODULE above rebinds the package attribute
# ``simulate`` to the module; the public name stays the legacy function
from determined_tpu.searcher._searcher import simulate  # noqa: E402,F811

__all__ = [
    "Bracket",
    "HyperbandSearch",
    "hyperband_brackets",
    "PBTSearch",
    "perturb_hparams",
    "JournalCurveModel",
    "SimulationReport",
    "SyntheticCurveModel",
    "compare_methods",
    "format_comparison",
    "simulate_method",
    "Action",
    "Create",
    "ExitedReason",
    "RequestID",
    "SearcherContext",
    "SearchMethod",
    "Shutdown",
    "Stop",
    "Searcher",
    "TrialRecord",
    "method_from_config",
    "simulate",
    "TournamentSearch",
    "make_adaptive_asha",
    "ASHASearch",
    "GridSearch",
    "RandomSearch",
    "SingleSearch",
]
