"""Hyperparameter search: single/random/grid/ASHA/adaptive-ASHA + simulation."""

from determined_tpu.searcher._base import (
    Action,
    Create,
    ExitedReason,
    RequestID,
    SearcherContext,
    SearchMethod,
    Shutdown,
    Stop,
)
from determined_tpu.searcher._searcher import (
    Searcher,
    TrialRecord,
    method_from_config,
    simulate,
)
from determined_tpu.searcher.adaptive import TournamentSearch, make_adaptive_asha
from determined_tpu.searcher.asha import ASHASearch
from determined_tpu.searcher.methods import GridSearch, RandomSearch, SingleSearch

__all__ = [
    "Action",
    "Create",
    "ExitedReason",
    "RequestID",
    "SearcherContext",
    "SearchMethod",
    "Shutdown",
    "Stop",
    "Searcher",
    "TrialRecord",
    "method_from_config",
    "simulate",
    "TournamentSearch",
    "make_adaptive_asha",
    "ASHASearch",
    "GridSearch",
    "RandomSearch",
    "SingleSearch",
]
