"""ASHA early-stopping search.

Reference: ``master/pkg/searcher/asha_stopping.go:21-291``.  Asynchronous
successive halving in its *stopping* formulation: every reported validation
metric is ranked within its rung; runs outside the top 1/divisor are
stopped, survivors continue toward the next rung.  Rung r needs
``max_time / divisor**(num_rungs-r-1)`` time units.
"""

from __future__ import annotations

import bisect
import logging
import math
from typing import Any, Dict, List, Optional

from determined_tpu.searcher._base import (
    Action,
    RequestID,
    SearcherContext,
    SearchMethod,
    Shutdown,
    Stop,
    ExitedReason,
)

logger = logging.getLogger(__name__)

ASHA_EXITED_METRIC = math.inf


class _Rung:
    def __init__(self, units_needed: int) -> None:
        self.units_needed = units_needed
        self.metrics: List[tuple] = []  # sorted [(metric, request_id)]

    def insert(self, request_id: RequestID, metric: float) -> int:
        idx = bisect.bisect_left([m for m, _ in self.metrics], metric)
        self.metrics.insert(idx, (metric, request_id))
        return idx

    def remove(self, request_id: RequestID) -> None:
        self.metrics = [(m, r) for m, r in self.metrics if r != request_id]


def make_rungs(num_rungs: int, divisor: float, max_units: int) -> List[_Rung]:
    return [
        _Rung(max(int(max_units / divisor ** (num_rungs - i - 1)), 1))
        for i in range(num_rungs)
    ]


class ASHASearch(SearchMethod):
    """Async-halving stopping search (one bracket)."""

    def __init__(
        self,
        *,
        metric: str,
        smaller_is_better: bool = True,
        max_time: int,
        time_metric: str = "batches",
        num_rungs: int = 5,
        divisor: float = 4.0,
        max_trials: int = 16,
        max_concurrent_trials: int = 0,
    ) -> None:
        self.metric = metric
        self.smaller_is_better = smaller_is_better
        self.time_metric = time_metric
        self.num_rungs = num_rungs
        self.divisor = divisor
        self.max_trials = max_trials
        self.max_concurrent_trials = max_concurrent_trials
        self.rungs = make_rungs(num_rungs, divisor, max_time)
        self.trial_rungs: Dict[RequestID, int] = {}
        self.early_exit_trials: Dict[RequestID, bool] = {}
        self.stopped_trials: set = set()
        self.trials_completed = 0
        self.invalid_trials = 0

    # -- events ------------------------------------------------------------

    def initial_trials(self, ctx: SearcherContext) -> List[Action]:
        if self.max_concurrent_trials > 0:
            n = min(self.max_concurrent_trials, self.max_trials)
        else:
            # enough parallelism that at least one run reaches the top rung
            n = max(1, min(int(self.divisor ** (self.num_rungs - 1)), self.max_trials))
        return [ctx.create() for _ in range(n)]

    def trial_created(self, ctx, request_id) -> List[Action]:
        self.trial_rungs[request_id] = 0
        return []

    def trial_exited(self, ctx, request_id) -> List[Action]:
        self.trials_completed += 1
        return []

    def _get_metric(self, metrics: Dict[str, Any]):
        value = metrics.get(self.metric)
        if not isinstance(value, (int, float)):
            raise ValueError(f"searcher metric {self.metric!r} missing from {metrics}")
        if not self.smaller_is_better:
            value = -value
        step = metrics.get(self.time_metric)
        if not isinstance(step, (int, float)):
            raise ValueError(
                f"searcher time metric {self.time_metric!r} missing from {metrics}"
            )
        return int(step), float(value)

    def validation_completed(self, ctx, request_id, metrics) -> List[Action]:
        if request_id in self.stopped_trials:
            # a stopped trial may report one or two more validations before
            # teardown; re-inserting would duplicate rung entries and burn
            # the trial budget on spurious replacement creates
            return []
        try:
            time_step, value = self._get_metric(metrics)
        except ValueError as e:
            # A malformed report (missing searcher/time metric) must not
            # abort the whole search; ignore it and let the trial keep
            # running — matching the reference's graceful degradation.
            logger.warning("ignoring unusable validation report for trial %s: %s",
                           request_id, e)
            return []
        actions = self._do_early_stopping(request_id, time_step, value)
        if any(isinstance(a, Stop) for a in actions):
            self.stopped_trials.add(request_id)
        all_trials = len(self.trial_rungs) - self.invalid_trials
        if actions and all_trials < self.max_trials:
            actions.append(ctx.create())
        return actions

    def _do_early_stopping(
        self, request_id: RequestID, time_step: int, metric: float
    ) -> List[Action]:
        actions: List[Action] = []
        for r in range(self.trial_rungs[request_id], self.num_rungs):
            rung = self.rungs[r]
            self.trial_rungs[request_id] = r
            if time_step < rung.units_needed:
                return actions
            insert_index = rung.insert(request_id, metric)
            if r == self.num_rungs - 1:
                actions.append(Stop(request_id))
                return actions
            # top 1/divisor continue; with < divisor entries only the best
            num_continue = max(int(len(rung.metrics) / self.divisor), 1)
            if insert_index >= num_continue:
                actions.append(Stop(request_id))
                return actions
        return actions

    def trial_exited_early(self, ctx, request_id, reason: str) -> List[Action]:
        if reason in (ExitedReason.INVALID_HP, ExitedReason.INIT_INVALID_HP):
            self.early_exit_trials[request_id] = True
            self.invalid_trials += 1
            for r in range(self.trial_rungs.get(request_id, 0) + 1):
                self.rungs[r].remove(request_id)
            return [Stop(request_id), ctx.create()]
        self.early_exit_trials[request_id] = True
        rung = self.rungs[self.trial_rungs.get(request_id, 0)]
        rung.insert(request_id, ASHA_EXITED_METRIC)
        actions: List[Action] = []
        if len(self.trial_rungs) - self.invalid_trials < self.max_trials:
            actions.append(ctx.create())
        return actions

    def progress(self, trial_progress, trials_closed) -> float:
        all_trials = len(self.rungs[0].metrics)
        # 20% overhead allowance while trials are still being created
        progress = all_trials / (1.2 * self.max_trials)
        if all_trials == self.max_trials:
            valid = self.trials_completed - self.invalid_trials
            progress = max(valid / self.max_trials, progress)
        return min(progress, 1.0)

    # -- snapshot ----------------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        return {
            "rungs": [
                {"units_needed": r.units_needed, "metrics": list(r.metrics)}
                for r in self.rungs
            ],
            "trial_rungs": dict(self.trial_rungs),
            "early_exit_trials": dict(self.early_exit_trials),
            "trials_completed": self.trials_completed,
            "invalid_trials": self.invalid_trials,
            "stopped_trials": sorted(self.stopped_trials),
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.rungs = []
        for r in state["rungs"]:
            rung = _Rung(r["units_needed"])
            rung.metrics = [tuple(m) for m in r["metrics"]]
            self.rungs.append(rung)
        self.trial_rungs = {int(k): v for k, v in state["trial_rungs"].items()}
        self.early_exit_trials = {
            int(k): v for k, v in state["early_exit_trials"].items()
        }
        self.trials_completed = state["trials_completed"]
        self.invalid_trials = state["invalid_trials"]
        self.stopped_trials = set(state.get("stopped_trials", []))
