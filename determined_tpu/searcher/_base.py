"""Searcher core: events, actions, SearchMethod interface.

Reference: ``master/pkg/searcher/search_method.go:17`` — an event-driven
interface; the experiment engine forwards trial lifecycle events and the
method returns actions (Create/Stop/Shutdown).  Semantics preserved;
implementation is Python (the search logic is control-plane, not TPU math).
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np

from determined_tpu.config.hyperparameters import sample_hyperparameters

# stable ids for trials created by the searcher
RequestID = int


@dataclasses.dataclass
class Create:
    request_id: RequestID
    hparams: Dict[str, Any]
    # PBT exploit provenance: clone the new trial's initial state from the
    # named trial's newest usable checkpoint (the driver resolves the uuid
    # through the manifest lineage walk; the searcher only names the trial)
    source_trial_id: Optional[RequestID] = None


@dataclasses.dataclass
class Stop:
    request_id: RequestID


@dataclasses.dataclass
class Shutdown:
    cancel: bool = False
    failure: bool = False


Action = Any  # Create | Stop | Shutdown


class ExitedReason:
    ERRORED = "errored"
    USER_CANCELED = "user_canceled"
    INVALID_HP = "invalid_hp"
    INIT_INVALID_HP = "init_invalid_hp"


class SearcherContext:
    """What a method needs to act: the hp space and a seeded rng."""

    def __init__(self, hparams: Dict[str, Any], seed: int = 0) -> None:
        self.hparams = hparams
        self.rand = np.random.default_rng(seed)
        self._next_id = 1

    def next_request_id(self) -> RequestID:
        rid = self._next_id
        self._next_id += 1
        return rid

    # snapshot/restore: id counter + rng must survive resumes or replacement
    # creates after a restore would reuse live request ids
    def state_dict(self) -> Dict[str, Any]:
        return {"next_id": self._next_id, "rng_state": self.rand.bit_generator.state}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._next_id = int(state["next_id"])
        self.rand.bit_generator.state = state["rng_state"]

    def sample(self) -> Dict[str, Any]:
        return sample_hyperparameters(self.hparams, self.rand)

    def create(
        self,
        hparams: Optional[Dict[str, Any]] = None,
        source_trial_id: Optional[RequestID] = None,
    ) -> Create:
        return Create(
            self.next_request_id(),
            hparams if hparams is not None else self.sample(),
            source_trial_id,
        )


class SearchMethod(abc.ABC):
    """Event-driven search algorithm (reference ``SearchMethod`` iface)."""

    @abc.abstractmethod
    def initial_trials(self, ctx: SearcherContext) -> List[Action]:
        ...

    def trial_created(self, ctx: SearcherContext, request_id: RequestID) -> List[Action]:
        return []

    @abc.abstractmethod
    def validation_completed(
        self, ctx: SearcherContext, request_id: RequestID, metrics: Dict[str, Any]
    ) -> List[Action]:
        ...

    def trial_exited(self, ctx: SearcherContext, request_id: RequestID) -> List[Action]:
        return []

    def trial_exited_early(
        self, ctx: SearcherContext, request_id: RequestID, reason: str
    ) -> List[Action]:
        return []

    @abc.abstractmethod
    def progress(
        self,
        trial_progress: Dict[RequestID, float],
        trials_closed: Dict[RequestID, bool],
    ) -> float:
        ...

    def clone_source_trials(self) -> List[RequestID]:
        """Trials whose checkpoints are LIVE clone sources.

        A method that clones from checkpoints (PBT exploit) names here
        every trial a future ``Create.source_trial_id`` may still point
        at; checkpoint GC must not delete those trials' latest checkpoints
        mid-generation even when top-k-by-metric retention would.
        """
        return []

    # snapshot/restore (reference Snapshot/Restore json round-trip)
    def state_dict(self) -> Dict[str, Any]:
        return {}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        ...
