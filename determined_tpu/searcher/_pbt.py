"""Population-based training (Jaderberg et al., 2017).

A fixed-size population trains in generations.  Every member runs for one
generation budget (``searcher.max_length`` — the same per-trial budget every
other method uses) and exits; when the whole generation has exited the
method ranks members by their last reported metric and turns the population
over:

- **exploit**: the bottom ``truncate_fraction`` of the population is
  replaced by children cloned from uniformly-drawn top-``truncate_fraction``
  survivors.  The method only *names* the parent trial
  (``Create.source_trial_id``); resolving which checkpoint uuid that means
  — newest usable in the parent's manifest lineage — and materializing it
  into the child's namespace is the driver's job (``experiment/local.py``),
  the same verified-parent machinery crash-resumes already use.
- **explore**: each exploited child's hyperparameters are perturbed —
  numeric hps multiply by ``perturb_factor`` or its inverse (clamped to the
  declared range), any hp resamples outright with
  ``resample_probability`` — all drawn from the journaled SearcherContext
  rng, so a replayed search perturbs identically.
- survivors continue as fresh trials cloned from their OWN latest
  checkpoint with unchanged hyperparameters (the reference PBT's
  "ready -> next interval" step, expressed in the create/stop event
  vocabulary the rest of the searcher zoo uses).

Trials that error out (or report no usable metric) rank worst: they are
never exploit parents and are always replaced.

Hyperparameters that only feed runtime state (a learning rate routed
through ``optax.inject_hyperparams``) should be declared in
``JaxTrial.compile_cache_runtime_hparams`` — lr-type perturbations then
reuse the cross-trial compiled step (``train/_jit_cache.py``) instead of
retracing every child.
"""

from __future__ import annotations

import logging
import math
from typing import Any, Dict, List, Optional

from determined_tpu.config.hyperparameters import (
    Categorical,
    Const,
    Double,
    Int,
    Log,
    _set_nested,
    _walk,
)
from determined_tpu.observability import get_tracer
from determined_tpu.searcher._base import (
    Action,
    RequestID,
    SearcherContext,
    SearchMethod,
    Shutdown,
)

logger = logging.getLogger(__name__)


def _get_nested(d: Dict[str, Any], path) -> Any:
    for k in path:
        d = d[k]
    return d


def perturb_hparams(
    space: Dict[str, Any],
    hparams: Dict[str, Any],
    rand,
    *,
    perturb_factor: float = 1.2,
    resample_probability: float = 0.25,
) -> Dict[str, Any]:
    """One PBT explore step over a concrete hparam dict.

    Numeric hps (int/double/log) multiply by ``perturb_factor`` or its
    inverse (fair coin) and clamp to the declared range; categorical/const
    hps can only change by resampling.  Every hp independently resamples
    outright with ``resample_probability``.  All draws come from ``rand``
    (the SearcherContext rng), which keeps explore deterministic under
    journal replay.
    """
    out: Dict[str, Any] = {}
    for path, hp in _walk(space):
        try:
            val = _get_nested(hparams, path)
        except (KeyError, TypeError):
            val = hp.sample(rand)
        if rand.random() < resample_probability:
            val = hp.sample(rand)
        elif isinstance(hp, (Int, Double, Log)):
            factor = perturb_factor if rand.random() < 0.5 else 1.0 / perturb_factor
            new = float(val) * factor
            if isinstance(hp, Log):
                lo, hi = hp.base ** hp.minval, hp.base ** hp.maxval
                val = min(max(new, lo), hi)
            elif isinstance(hp, Int):
                val = int(round(min(max(new, hp.minval), hp.maxval)))
            else:
                val = min(max(new, hp.minval), hp.maxval)
        elif isinstance(hp, (Categorical, Const)):
            pass  # keep; only the resample branch changes these
        _set_nested(out, path, val)
    return out


class PBTSearch(SearchMethod):
    """Generation-synchronous population-based training."""

    def __init__(
        self,
        *,
        metric: str,
        smaller_is_better: bool = True,
        population_size: int = 8,
        num_generations: int = 4,
        truncate_fraction: float = 0.25,
        perturb_factor: float = 1.2,
        resample_probability: float = 0.25,
        time_metric: str = "batches",
    ) -> None:
        if population_size < 1:
            raise ValueError("pbt population_size must be >= 1")
        if num_generations < 1:
            raise ValueError("pbt num_generations must be >= 1")
        if not 0.0 <= truncate_fraction <= 0.5:
            raise ValueError("pbt truncate_fraction must be in [0, 0.5]")
        if perturb_factor <= 1.0:
            raise ValueError("pbt perturb_factor must be > 1")
        self.metric = metric
        self.smaller_is_better = smaller_is_better
        self.population_size = population_size
        self.num_generations = num_generations
        self.truncate_fraction = truncate_fraction
        self.perturb_factor = perturb_factor
        self.resample_probability = resample_probability
        self.time_metric = time_metric
        # slot-ordered members of the CURRENT generation
        self.generation = 0
        self.members: List[Dict[str, Any]] = []  # {rid, metric, exited}
        self.prev_rids: List[RequestID] = []     # last generation (clone srcs)
        self.hparams: Dict[RequestID, Dict[str, Any]] = {}
        self.lineage: Dict[RequestID, Optional[RequestID]] = {}
        self.trials_completed = 0

    # -- events ------------------------------------------------------------

    def initial_trials(self, ctx: SearcherContext) -> List[Action]:
        actions: List[Action] = []
        for _ in range(self.population_size):
            a = ctx.create()
            self.hparams[a.request_id] = a.hparams
            self.lineage[a.request_id] = None
            self.members.append({"rid": a.request_id, "metric": None, "exited": False})
            actions.append(a)
        return actions

    def _member(self, request_id: RequestID) -> Optional[Dict[str, Any]]:
        for m in self.members:
            if m["rid"] == request_id:
                return m
        return None

    def validation_completed(self, ctx, request_id, metrics) -> List[Action]:
        m = self._member(request_id)
        if m is None or m["exited"]:
            return []
        value = metrics.get(self.metric)
        # NaN/inf must rank WORST, not sort-first: a diverged member that
        # reported nan would otherwise become everyone's exploit parent
        if isinstance(value, (int, float)) and math.isfinite(value):
            m["metric"] = float(value)  # last report wins: end-of-generation fitness
        else:
            # and it INVALIDATES earlier finite reports: the member's
            # latest state is what a clone would inherit
            m["metric"] = None
            logger.warning(
                "pbt: trial %s reported no usable %r (%r); it will rank worst",
                request_id, self.metric, value,
            )
        return []

    def trial_exited(self, ctx, request_id) -> List[Action]:
        m = self._member(request_id)
        if m is None or m["exited"]:
            return []
        m["exited"] = True
        self.trials_completed += 1
        if not all(mm["exited"] for mm in self.members):
            return []
        return self._turnover(ctx)

    def trial_exited_early(self, ctx, request_id, reason: str) -> List[Action]:
        # an errored/invalid member ranks worst (metric None) and is always
        # replaced at turnover; the generation must not deadlock on it
        m = self._member(request_id)
        if m is None or m["exited"]:
            return []
        m["metric"] = None
        return self.trial_exited(ctx, request_id)

    # -- the generation boundary -------------------------------------------

    def _rank(self) -> List[Dict[str, Any]]:
        """Members best-first; metric-less members always rank last."""
        sign = 1.0 if self.smaller_is_better else -1.0
        return sorted(
            self.members,
            key=lambda m: (m["metric"] is None,
                           sign * (m["metric"] if m["metric"] is not None else 0.0)),
        )

    def _turnover(self, ctx: SearcherContext) -> List[Action]:
        if self.generation + 1 >= self.num_generations:
            return [Shutdown()]
        ranked = self._rank()
        n = self.population_size
        # truncate_fraction == 0 means pure continuation (no exploitation);
        # any positive fraction replaces at least one member
        if n < 2 or self.truncate_fraction == 0.0:
            k = 0
        else:
            k = max(1, int(n * self.truncate_fraction))
        # exploit parents must have REPORTED the searcher metric: cloning a
        # crashed/silent member would seed children from a config with no
        # usable fitness (and possibly no checkpoint)
        reporting = [m for m in ranked if m["metric"] is not None]
        top = reporting[: max(k, 1)] if reporting else []
        bottom = ranked[n - k:] if k else []
        replaced = {m["rid"] for m in bottom}
        actions: List[Action] = []
        next_members: List[Dict[str, Any]] = []
        clones = 0
        for m in self.members:
            rid = m["rid"]
            if rid in replaced and top:
                # exploit: clone a uniformly-drawn top survivor, explore its hps
                parent = top[int(ctx.rand.integers(0, len(top)))]["rid"]
                child_hp = perturb_hparams(
                    ctx.hparams,
                    self.hparams.get(parent, {}),
                    ctx.rand,
                    perturb_factor=self.perturb_factor,
                    resample_probability=self.resample_probability,
                )
                a = ctx.create(child_hp, source_trial_id=parent)
                clones += 1
            elif rid in replaced:
                # nobody reported a metric this generation: nothing worth
                # exploiting — replace with a fresh independent sample
                a = ctx.create()
            else:
                # survivor: continue from its own checkpoint, hps unchanged
                a = ctx.create(dict(self.hparams.get(rid, {})), source_trial_id=rid)
            self.hparams[a.request_id] = a.hparams
            self.lineage[a.request_id] = a.source_trial_id
            next_members.append({"rid": a.request_id, "metric": None, "exited": False})
            actions.append(a)
        self.prev_rids = [m["rid"] for m in self.members]
        self.members = next_members
        self.generation += 1
        best = ranked[0]
        get_tracer().instant(
            "searcher.pbt.generation",
            cat="searcher",
            generation=self.generation,
            best_trial=best["rid"],
            best_metric=best["metric"],
            exploited=len(replaced),
        )
        get_tracer().counter("searcher.pbt.clones", float(clones))
        logger.info(
            "pbt: generation %d -> %d: best trial %d (%s=%s), %d of %d exploited",
            self.generation - 1, self.generation, best["rid"], self.metric,
            best["metric"], len(replaced), n,
        )
        return actions

    # -- bookkeeping -------------------------------------------------------

    def clone_source_trials(self) -> List[RequestID]:
        # every current member is a candidate exploit parent until the NEXT
        # turnover, and the previous generation stays referenced until its
        # children have materialized their clones and checkpointed
        return sorted({m["rid"] for m in self.members} | set(self.prev_rids))

    def progress(self, trial_progress, trials_closed) -> float:
        total = self.population_size * self.num_generations
        done = self.trials_completed + sum(
            trial_progress.get(m["rid"], 0.0)
            for m in self.members
            if not m["exited"]
        )
        return min(done / total, 1.0)

    def state_dict(self) -> Dict[str, Any]:
        return {
            "generation": self.generation,
            "members": [dict(m) for m in self.members],
            "prev_rids": list(self.prev_rids),
            "hparams": {str(r): hp for r, hp in self.hparams.items()},
            "lineage": {str(r): p for r, p in self.lineage.items()},
            "trials_completed": self.trials_completed,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.generation = int(state["generation"])
        self.members = [dict(m) for m in state["members"]]
        self.prev_rids = [int(r) for r in state.get("prev_rids", [])]
        self.hparams = {int(r): hp for r, hp in state["hparams"].items()}
        self.lineage = {
            int(r): (None if p is None else int(p))
            for r, p in state["lineage"].items()
        }
        self.trials_completed = int(state["trials_completed"])
