"""Hyperband (Li et al., 2018): the full successive-halving bracket schedule.

Canonical bracket math over a per-trial budget ``R`` (``max_time``) and an
elimination rate ``eta`` (``divisor``): with ``s_max = floor(log_eta R)``,
brackets ``s = s_max .. 0`` each run successive halving starting from

    n_s = ceil((s_max + 1) / (s + 1) * eta**s)   configs
    r_s = R / eta**s                             initial resource

so every bracket spends roughly the same total budget while trading off
"many configs, early stopping" (s = s_max) against "few configs, full
budget" (s = 0).

Execution maps each bracket onto the rung machinery ASHA already uses
(``asha.ASHASearch`` with ``num_rungs = s + 1`` produces exactly the
``r_s * eta**i`` rung schedule), promoted/stopped through the same event
vocabulary: a trial that ranks in the top ``1/eta`` of its rung continues
(is promoted to train toward the next rung), the rest receive ``Stop``.
Rung decisions are made as metrics arrive rather than at a synchronous
barrier — the *asynchronous* Hyperband formulation the ASHA paper
motivates, which never parks a trial waiting for rung stragglers.

Events route through ``TournamentSearch``, so snapshot/restore and journal
replay reuse the bracket-tested adaptive-ASHA paths.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional

from determined_tpu.searcher.adaptive import TournamentSearch
from determined_tpu.searcher.asha import ASHASearch


@dataclasses.dataclass(frozen=True)
class Bracket:
    """One Hyperband bracket, as the canonical schedule defines it."""

    s: int                 # aggressiveness: rungs below the top one
    n_trials: int          # configs the bracket starts with
    min_resource: int      # units a trial trains before its first rung
    num_rungs: int         # s + 1

    def rung_schedule(self, max_time: int, eta: float) -> List[int]:
        return [
            max(int(max_time / eta ** (self.num_rungs - i - 1)), 1)
            for i in range(self.num_rungs)
        ]


def hyperband_brackets(max_time: int, divisor: float) -> List[Bracket]:
    """The canonical (s, n_s, r_s) schedule, most aggressive bracket first."""
    if max_time < 1:
        raise ValueError("hyperband needs max_time >= 1")
    if divisor <= 1:
        raise ValueError("hyperband needs divisor > 1")
    # epsilon before truncating: log(1000)/log(10) is 2.9999999999999996
    # in floats, and losing the most aggressive bracket silently breaks
    # the published schedule for every R that is an exact power of eta
    s_max = int(math.log(max_time) / math.log(divisor) + 1e-9)
    out = []
    for s in range(s_max, -1, -1):
        n = math.ceil((s_max + 1) / (s + 1) * divisor ** s)
        out.append(
            Bracket(
                s=s,
                n_trials=int(n),
                min_resource=max(int(max_time / divisor ** s), 1),
                num_rungs=s + 1,
            )
        )
    return out


class HyperbandSearch(TournamentSearch):
    """All Hyperband brackets run concurrently as a tournament.

    ``max_trials`` (when > 1) caps the canonical schedule: brackets are
    trimmed from the least-aggressive end, the same policy adaptive ASHA's
    budget split uses.  ``max_trials <= 1`` (the config default) means "run
    the canonical schedule as published".
    """

    def __init__(
        self,
        *,
        metric: str,
        smaller_is_better: bool = True,
        max_time: int,
        time_metric: str = "batches",
        divisor: float = 3.0,
        max_trials: int = 0,
        max_concurrent_trials: int = 0,
    ) -> None:
        self.metric = metric
        self.max_time = max_time
        self.divisor = divisor
        brackets = hyperband_brackets(max_time, divisor)
        if max_trials > 1:
            budget = max_trials
            trimmed = []
            for b in brackets:
                take = min(b.n_trials, budget)
                budget -= take
                if take > 0:
                    trimmed.append(dataclasses.replace(b, n_trials=take))
            brackets = trimmed
        self.brackets = brackets
        subs = [
            ASHASearch(
                metric=metric,
                smaller_is_better=smaller_is_better,
                max_time=max_time,
                time_metric=time_metric,
                num_rungs=b.num_rungs,
                divisor=divisor,
                max_trials=b.n_trials,
                # the whole bracket is created up front (the canonical
                # schedule's n_s); actual parallelism is still capped by
                # the experiment's device-derived concurrency
                max_concurrent_trials=(
                    min(max_concurrent_trials, b.n_trials)
                    if max_concurrent_trials > 0
                    else b.n_trials
                ),
            )
            for b in brackets
        ]
        super().__init__(subs)

    def describe(self) -> List[Dict[str, Any]]:
        """Bracket table for reports (`dtpu searcher simulate`, docs)."""
        return [
            {
                "s": b.s,
                "trials": b.n_trials,
                "min_resource": b.min_resource,
                "rungs": b.rung_schedule(self.max_time, self.divisor),
            }
            for b in self.brackets
        ]

    def bracket_of(self, request_id: int) -> Optional[int]:
        """Bracket ``s`` owning a trial (None before its create lands)."""
        i = self.owner.get(request_id)
        return self.brackets[i].s if i is not None else None
