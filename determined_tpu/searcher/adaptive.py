"""Adaptive ASHA: bracket allocation + tournament of ASHA sub-searches.

Reference: ``master/pkg/searcher/adaptive_asha.go:84-154`` (brackets, modes
conservative/standard/aggressive, budget-weighted trial allocation) and
``tournament.go:25`` (event routing to the owning sub-search).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

from determined_tpu.searcher._base import (
    Action,
    Create,
    RequestID,
    SearcherContext,
    SearchMethod,
    Shutdown,
)
from determined_tpu.searcher.asha import ASHASearch


def bracket_rungs_for_mode(mode: str, max_rungs: int) -> List[int]:
    if mode == "conservative":
        return list(range(1, max_rungs + 1))
    if mode == "standard":
        return list(range((max_rungs - 1) // 2 + 1, max_rungs + 1))
    if mode == "aggressive":
        return [max_rungs]
    raise ValueError(f"unknown adaptive mode {mode!r}")


def bracket_max_trials(max_trials: int, divisor: float, brackets: List[int]) -> List[int]:
    """Budget-weighted split: each bracket gets trials inversely proportional
    to its per-trial cost so total step budget is roughly equal."""
    weights = [divisor ** (n - 1) / n for n in brackets]
    total = sum(weights)
    out = [max(int(w / total * max_trials), 1) for w in weights]
    out[0] += max(max_trials - sum(out), 0)
    # the per-bracket minimum of 1 can overshoot when max_trials < #brackets:
    # trim from the least-aggressive (last) brackets down to the cap
    excess = sum(out) - max_trials
    for i in range(len(out) - 1, 0, -1):
        if excess <= 0:
            break
        take = min(excess, out[i])
        out[i] -= take
        excess -= take
    return out


def bracket_max_concurrent(
    max_concurrent_trials: int, divisor: float, max_trials: List[int]
) -> List[int]:
    n = len(max_trials)
    if max_concurrent_trials == 0:
        base = max(max_trials[-1], int(divisor))
        return [base] * n
    max_concurrent_trials = max(max_concurrent_trials, n)
    base, rem = divmod(max_concurrent_trials, n)
    out = [base] * n
    for i in range(rem):
        out[i] += 1
    return out


class TournamentSearch(SearchMethod):
    """Routes each trial's events to the sub-search that created it."""

    def __init__(self, subs: List[SearchMethod]) -> None:
        self.subs = subs
        self.owner: Dict[RequestID, int] = {}
        self.closed = [False] * len(subs)

    def _mark(self, sub_id: int, actions: List[Action]) -> List[Action]:
        out: List[Action] = []
        for a in actions:
            if isinstance(a, Create):
                self.owner[a.request_id] = sub_id
                out.append(a)
            elif isinstance(a, Shutdown):
                self.closed[sub_id] = True
                if all(self.closed):
                    out.append(a)
            else:
                out.append(a)
        return out

    def initial_trials(self, ctx: SearcherContext) -> List[Action]:
        out: List[Action] = []
        for i, sub in enumerate(self.subs):
            out.extend(self._mark(i, sub.initial_trials(ctx)))
        return out

    def trial_created(self, ctx, request_id) -> List[Action]:
        i = self.owner[request_id]
        return self._mark(i, self.subs[i].trial_created(ctx, request_id))

    def validation_completed(self, ctx, request_id, metrics) -> List[Action]:
        i = self.owner[request_id]
        return self._mark(i, self.subs[i].validation_completed(ctx, request_id, metrics))

    def trial_exited(self, ctx, request_id) -> List[Action]:
        i = self.owner[request_id]
        return self._mark(i, self.subs[i].trial_exited(ctx, request_id))

    def trial_exited_early(self, ctx, request_id, reason) -> List[Action]:
        i = self.owner[request_id]
        return self._mark(i, self.subs[i].trial_exited_early(ctx, request_id, reason))

    def progress(self, trial_progress, trials_closed) -> float:
        per_sub_progress: List[Dict[RequestID, float]] = [
            {} for _ in self.subs
        ]
        per_sub_closed: List[Dict[RequestID, bool]] = [{} for _ in self.subs]
        for rid, p in trial_progress.items():
            if rid in self.owner:
                per_sub_progress[self.owner[rid]][rid] = p
        for rid, c in trials_closed.items():
            if rid in self.owner:
                per_sub_closed[self.owner[rid]][rid] = c
        if not self.subs:
            return 1.0
        return sum(
            s.progress(p, c)
            for s, p, c in zip(self.subs, per_sub_progress, per_sub_closed)
        ) / len(self.subs)

    def state_dict(self) -> Dict[str, Any]:
        return {
            "subs": [s.state_dict() for s in self.subs],
            "owner": dict(self.owner),
            "closed": list(self.closed),
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        for sub, s in zip(self.subs, state["subs"]):
            sub.load_state_dict(s)
        self.owner = {int(k): v for k, v in state["owner"].items()}
        self.closed = list(state["closed"])


def make_adaptive_asha(
    *,
    metric: str,
    smaller_is_better: bool = True,
    max_time: int,
    time_metric: str = "batches",
    max_trials: int = 16,
    max_rungs: int = 5,
    divisor: float = 4.0,
    mode: str = "standard",
    max_concurrent_trials: int = 0,
    bracket_rungs: Optional[List[int]] = None,
) -> TournamentSearch:
    if not bracket_rungs:
        capped = min(
            max_rungs,
            int(math.log(max(max_time, 2)) / math.log(divisor)) + 1,
            int(math.log(max(max_trials, 2)) / math.log(divisor)) + 1,
        )
        bracket_rungs = bracket_rungs_for_mode(mode, max(capped, 1))
    # most-aggressive (deepest) brackets first
    bracket_rungs = sorted(bracket_rungs, reverse=True)
    trials = bracket_max_trials(max_trials, divisor, bracket_rungs)
    concurrent = bracket_max_concurrent(max_concurrent_trials, divisor, trials)
    subs: List[SearchMethod] = [
        ASHASearch(
            metric=metric,
            smaller_is_better=smaller_is_better,
            max_time=max_time,
            time_metric=time_metric,
            num_rungs=nr,
            divisor=divisor,
            max_trials=nt,
            max_concurrent_trials=nc,
        )
        for nr, nt, nc in zip(bracket_rungs, trials, concurrent)
        if nt > 0  # brackets trimmed to honor a small max_trials cap
    ]
    return TournamentSearch(subs)
