"""Cloud storage backends: S3 / GCS / Azure (reference: common/storage/{s3,gcs,azure}.py).

The runtime image does not bake boto3 / google-cloud-storage / azure SDKs;
these managers import lazily and raise a clear error when unavailable, so
`from_string("s3://...")` still parses and the rest of the platform is
unaffected.
"""

from __future__ import annotations

import os
import posixpath
from typing import Callable, Dict, List, Optional

from determined_tpu.storage.base import StorageManager, list_directory
from determined_tpu.utils.errors import CheckpointNotFoundError


class _BlobStorageManager(StorageManager):
    """Shared logic over a minimal blob client interface."""

    def __init__(self, bucket: str, prefix: str = "") -> None:
        self.bucket = bucket
        self.prefix = prefix.strip("/")

    @classmethod
    def from_url(cls, url: str, **kwargs) -> "_BlobStorageManager":
        rest = url.split("://", 1)[1]
        bucket, _, prefix = rest.partition("/")
        return cls(bucket, prefix, **kwargs)

    def _key(self, storage_id: str, rel: str = "") -> str:
        parts = [p for p in (self.prefix, storage_id, rel) if p]
        return posixpath.join(*parts)

    # blob primitives supplied by subclasses
    def _put(self, key: str, local_path: str) -> None:
        raise NotImplementedError

    def _get(self, key: str, local_path: str) -> None:
        raise NotImplementedError

    def _list(self, key_prefix: str) -> Dict[str, int]:
        raise NotImplementedError

    def _delete(self, keys: List[str]) -> None:
        raise NotImplementedError

    def _upload(self, src, storage_id, paths=None, progress=None) -> None:
        names = paths if paths is not None else list(list_directory(src))
        done = 0
        for rel in names:
            if rel.endswith("/"):
                continue
            self._put(self._key(storage_id, rel), os.path.join(src, rel))
            done += 1
            if progress:
                progress(done)

    def _download(self, storage_id, dst, selector=None) -> None:
        base = self._key(storage_id)
        files = self._list(base)
        if not files:
            raise CheckpointNotFoundError(f"checkpoint {storage_id} not found in {self.bucket}")
        for rel in files:
            if selector is not None and not selector(rel):
                continue
            local = os.path.join(dst, rel)
            os.makedirs(os.path.dirname(local) or dst, exist_ok=True)
            self._get(posixpath.join(base, rel), local)

    def delete(self, storage_id, globs=None) -> Dict[str, int]:
        import fnmatch

        base = self._key(storage_id)
        files = self._list(base)
        if globs is None:
            self._delete([posixpath.join(base, rel) for rel in files])
            return {}
        doomed = [
            rel
            for rel in files
            if any(fnmatch.fnmatch(rel, g) or fnmatch.fnmatch("/" + rel, g) for g in globs)
        ]
        self._delete([posixpath.join(base, rel) for rel in doomed])
        return {rel: sz for rel, sz in files.items() if rel not in set(doomed)}

    def list_files(self, storage_id) -> Dict[str, int]:
        return self._list(self._key(storage_id))


class S3StorageManager(_BlobStorageManager):
    def __init__(self, bucket: str, prefix: str = "", endpoint_url: Optional[str] = None) -> None:
        super().__init__(bucket, prefix)
        try:
            import boto3  # type: ignore
        except ImportError as e:
            raise ImportError(
                "s3:// storage requires boto3, which is not installed in this image"
            ) from e
        self._client = boto3.client("s3", endpoint_url=endpoint_url)

    def _put(self, key, local_path):
        self._client.upload_file(local_path, self.bucket, key)

    def _get(self, key, local_path):
        self._client.download_file(self.bucket, key, local_path)

    def _list(self, key_prefix):
        out: Dict[str, int] = {}
        paginator = self._client.get_paginator("list_objects_v2")
        for page in paginator.paginate(Bucket=self.bucket, Prefix=key_prefix + "/"):
            for obj in page.get("Contents", []):
                out[posixpath.relpath(obj["Key"], key_prefix)] = obj["Size"]
        return out

    def _delete(self, keys):
        for i in range(0, len(keys), 1000):
            self._client.delete_objects(
                Bucket=self.bucket,
                Delete={"Objects": [{"Key": k} for k in keys[i : i + 1000]]},
            )


class GCSStorageManager(_BlobStorageManager):
    def __init__(self, bucket: str, prefix: str = "") -> None:
        super().__init__(bucket, prefix)
        try:
            from google.cloud import storage  # type: ignore
        except ImportError as e:
            raise ImportError(
                "gs:// storage requires google-cloud-storage, not installed in this image"
            ) from e
        self._bucket = storage.Client().bucket(bucket)

    def _put(self, key, local_path):
        self._bucket.blob(key).upload_from_filename(local_path)

    def _get(self, key, local_path):
        self._bucket.blob(key).download_to_filename(local_path)

    def _list(self, key_prefix):
        return {
            posixpath.relpath(b.name, key_prefix): b.size
            for b in self._bucket.list_blobs(prefix=key_prefix + "/")
        }

    def _delete(self, keys):
        for k in keys:
            self._bucket.blob(k).delete()


class AzureStorageManager(_BlobStorageManager):
    def __init__(self, container: str, prefix: str = "", connection_string: Optional[str] = None) -> None:
        super().__init__(container, prefix)
        try:
            from azure.storage.blob import BlobServiceClient  # type: ignore
        except ImportError as e:
            raise ImportError(
                "azure:// storage requires azure-storage-blob, not installed in this image"
            ) from e
        conn = connection_string or os.environ.get("AZURE_STORAGE_CONNECTION_STRING", "")
        svc = BlobServiceClient.from_connection_string(conn)
        self._container = svc.get_container_client(container)

    def _put(self, key, local_path):
        with open(local_path, "rb") as f:
            self._container.upload_blob(key, f, overwrite=True)

    def _get(self, key, local_path):
        with open(local_path, "wb") as f:
            f.write(self._container.download_blob(key).readall())

    def _list(self, key_prefix):
        return {
            posixpath.relpath(b.name, key_prefix): b.size
            for b in self._container.list_blobs(name_starts_with=key_prefix + "/")
        }

    def _delete(self, keys):
        for k in keys:
            self._container.delete_blob(k)
