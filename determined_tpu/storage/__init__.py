from determined_tpu.storage.base import (  # noqa: F401
    StorageManager,
    from_expconf,
    from_string,
    file_md5,
    list_directory,
)
from determined_tpu.storage.shared_fs import (  # noqa: F401
    SharedFSStorageManager,
    DirectoryStorageManager,
)
