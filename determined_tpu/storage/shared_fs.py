"""Filesystem storage backends (reference: common/storage/shared.py, directory.py)."""

from __future__ import annotations

import fnmatch
import os
import shutil
from typing import Callable, Dict, List, Optional

from determined_tpu.storage.base import StorageManager, list_directory
from determined_tpu.utils.errors import CheckpointNotFoundError


class SharedFSStorageManager(StorageManager):
    """Checkpoints live under a shared filesystem root visible to all hosts."""

    direct_store = True

    def __init__(self, base_path: str) -> None:
        self.base_path = os.path.abspath(base_path)

    def _ckpt_dir(self, storage_id: str) -> str:
        return os.path.join(self.base_path, storage_id)

    def _upload(self, src, storage_id, paths=None, progress=None) -> None:
        dst = self._ckpt_dir(storage_id)
        os.makedirs(dst, exist_ok=True)
        names = paths if paths is not None else list(list_directory(src))
        done = 0
        for rel in names:
            s, d = os.path.join(src, rel), os.path.join(dst, rel)
            if rel.endswith("/"):
                os.makedirs(d, exist_ok=True)
                continue
            os.makedirs(os.path.dirname(d), exist_ok=True)
            shutil.copy2(s, d)
            done += 1
            if progress:
                progress(done)

    def _download(
        self, storage_id: str, dst: str, selector: Optional[Callable[[str], bool]] = None
    ) -> None:
        src = self._ckpt_dir(storage_id)
        if not os.path.isdir(src):
            raise CheckpointNotFoundError(f"checkpoint {storage_id} not in {self.base_path}")
        for rel, size in list_directory(src).items():
            if rel.endswith("/"):
                os.makedirs(os.path.join(dst, rel), exist_ok=True)
                continue
            if selector is not None and not selector(rel):
                continue
            d = os.path.join(dst, rel)
            os.makedirs(os.path.dirname(d), exist_ok=True)
            shutil.copy2(os.path.join(src, rel), d)

    def delete(self, storage_id: str, globs: Optional[List[str]] = None) -> Dict[str, int]:
        root = self._ckpt_dir(storage_id)
        if not os.path.isdir(root):
            raise CheckpointNotFoundError(f"checkpoint {storage_id} not in {self.base_path}")
        if globs is None:
            shutil.rmtree(root)
            return {}
        for rel in list(list_directory(root)):
            if rel.endswith("/"):
                continue
            if any(fnmatch.fnmatch(rel, g) or fnmatch.fnmatch("/" + rel, g) for g in globs):
                os.remove(os.path.join(root, rel))
        # prune empty dirs bottom-up (re-check with listdir: walk's dirnames
        # snapshot predates children we just removed)
        for dirpath, _dirnames, _filenames in os.walk(root, topdown=False):
            if dirpath != root and not os.listdir(dirpath):
                os.rmdir(dirpath)
        return list_directory(root)

    def list_files(self, storage_id: str) -> Dict[str, int]:
        root = self._ckpt_dir(storage_id)
        if not os.path.isdir(root):
            raise CheckpointNotFoundError(f"checkpoint {storage_id} not in {self.base_path}")
        return list_directory(root)

    def store_path(self, storage_id: str, staging_dir: str):
        """Write directly into the shared-fs checkpoint dir (no copy)."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            dst = self._ckpt_dir(storage_id)
            os.makedirs(dst, exist_ok=True)
            yield dst

        return cm()

    def restore_path(self, storage_id: str, staging_dir: str):
        """Read directly from the shared-fs checkpoint dir (no copy)."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            src = self._ckpt_dir(storage_id)
            if not os.path.isdir(src):
                raise CheckpointNotFoundError(
                    f"checkpoint {storage_id} not in {self.base_path}"
                )
            yield src

        return cm()


class DirectoryStorageManager(SharedFSStorageManager):
    """Same as shared_fs but semantically a container-local bind mount
    (reference: common/storage/directory.py)."""
