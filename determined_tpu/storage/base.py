"""Storage manager interface (reference: harness/determined/common/storage/).

A StorageManager moves checkpoint directories between a local staging path
and durable storage.  Backends: shared_fs, directory (bind-mounted),
s3/gcs/azure (gated on their SDKs).  ``from_string`` parses
"s3://bucket/prefix"-style URLs like the reference's
``storage/__init__.py from_string``.
"""

from __future__ import annotations

import abc
import contextlib
import hashlib
import os
import shutil
from typing import Callable, Dict, Iterator, List, Optional

from determined_tpu.utils import faults


def file_md5(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def list_directory(root: str) -> Dict[str, int]:
    """Relative-path -> size map of every file under root (dirs get size 0,
    trailing slash), matching the reference's resources dict shape."""
    out: Dict[str, int] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        rel = os.path.relpath(dirpath, root)
        if rel != ".":
            out[rel + "/"] = 0
        for fn in filenames:
            full = os.path.join(dirpath, fn)
            out[os.path.join("" if rel == "." else rel, fn)] = os.path.getsize(full)
    return out


class StorageManager(abc.ABC):
    """Upload/download whole checkpoint directories keyed by storage_id.

    ``upload``/``download`` are template methods wrapping the backend
    ``_upload``/``_download`` implementations so every backend shares the
    fault-injection hook points (``utils/faults.py``) — a test can fail
    the Nth put or drop a get on any backend without patching it.
    """

    # True when store_path/restore_path expose the durable directory itself
    # (shared_fs): no staging copy, and every rank may use the same path.
    direct_store = False

    def upload(
        self,
        src: str,
        storage_id: str,
        paths: Optional[List[str]] = None,
        progress: Optional[Callable[[int], None]] = None,
    ) -> None:
        faults.fire(
            "storage.upload", manager=self, src=src, storage_id=storage_id, paths=paths
        )
        self._upload(src, storage_id, paths=paths, progress=progress)
        faults.fire(
            "storage.upload.done", manager=self, src=src, storage_id=storage_id, paths=paths
        )

    def download(
        self,
        storage_id: str,
        dst: str,
        selector: Optional[Callable[[str], bool]] = None,
    ) -> None:
        faults.fire("storage.download", manager=self, storage_id=storage_id, dst=dst)
        self._download(storage_id, dst, selector=selector)

    @abc.abstractmethod
    def _upload(
        self,
        src: str,
        storage_id: str,
        paths: Optional[List[str]] = None,
        progress: Optional[Callable[[int], None]] = None,
    ) -> None:
        ...

    @abc.abstractmethod
    def _download(
        self,
        storage_id: str,
        dst: str,
        selector: Optional[Callable[[str], bool]] = None,
    ) -> None:
        ...

    @abc.abstractmethod
    def delete(self, storage_id: str, globs: Optional[List[str]] = None) -> Dict[str, int]:
        """Delete (all or glob-matched) files; returns remaining resources."""

    @abc.abstractmethod
    def list_files(self, storage_id: str) -> Dict[str, int]:
        ...

    @contextlib.contextmanager
    def restore_path(self, storage_id: str, staging_dir: str) -> Iterator[str]:
        """Download into a staging dir, yield it, clean up after."""
        dst = os.path.join(staging_dir, storage_id)
        os.makedirs(dst, exist_ok=True)
        self.download(storage_id, dst)
        try:
            yield dst
        finally:
            shutil.rmtree(dst, ignore_errors=True)

    def stage_path(self, storage_id: str, staging_dir: str) -> str:
        """Deterministic per-storage_id staging dir.

        Every local rank of a sharded checkpoint must stage into the SAME
        directory (collective array writers like orbax assume one directory
        per host); storage_id is a fresh uuid so ids never collide.  The
        caller owns upload and cleanup coordination across ranks —
        CheckpointContext.store_path(shard=True) does that.
        """
        path = os.path.join(staging_dir, storage_id)
        os.makedirs(path, exist_ok=True)
        return path

    # Backends that expose checkpoints as plain paths (shared_fs) override
    # store_path to avoid the copy; default stages then uploads.
    @contextlib.contextmanager
    def store_path(self, storage_id: str, staging_dir: str) -> Iterator[str]:
        """Single-process staging: stage, upload on success, clean up.

        Only one process may use this per storage_id; multi-rank sharded
        staging goes through CheckpointContext, which sequences upload and
        cleanup across ranks on top of stage_path().
        """
        src = self.stage_path(storage_id, staging_dir)
        try:
            yield src
            self.upload(src, storage_id)
        finally:
            shutil.rmtree(src, ignore_errors=True)


def from_expconf(raw: dict) -> "StorageManager":
    """StorageManager from an expconf checkpoint_storage dict — the single
    resolution used by core.init and SDK Checkpoint.download."""
    from determined_tpu.config.experiment import CheckpointStorageConfig

    return from_string(CheckpointStorageConfig.parse(dict(raw)).to_url())


def from_string(url: str, **kwargs) -> StorageManager:
    """Build a StorageManager from a URL-ish string.

    - "/abs/path" or "shared_fs:///abs/path" -> SharedFSStorageManager
    - "directory:///abs/path" -> DirectoryStorageManager
    - "s3://bucket/prefix", "gs://...", "azure://..." -> cloud backends
      (raise if their SDK is unavailable in this image).
    """
    from determined_tpu.storage.shared_fs import SharedFSStorageManager, DirectoryStorageManager

    if url.startswith("shared_fs://"):
        return SharedFSStorageManager(url[len("shared_fs://"):], **kwargs)
    if url.startswith("directory://"):
        return DirectoryStorageManager(url[len("directory://"):], **kwargs)
    if url.startswith("s3://"):
        from determined_tpu.storage.cloud import S3StorageManager

        return S3StorageManager.from_url(url, **kwargs)
    if url.startswith(("gs://", "gcs://")):
        from determined_tpu.storage.cloud import GCSStorageManager

        return GCSStorageManager.from_url(url, **kwargs)
    if url.startswith("azure://"):
        from determined_tpu.storage.cloud import AzureStorageManager

        return AzureStorageManager.from_url(url, **kwargs)
    if "://" in url:
        raise ValueError(f"unknown storage scheme: {url}")
    return SharedFSStorageManager(url, **kwargs)
