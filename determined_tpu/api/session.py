"""HTTP session to the master (reference: ``common/api/_session.py``).

requests-based with bounded retries, bearer-token auth, and base-url
joining.  This is the single transport used by the Core API contexts, the
SDK, and the CLI.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, Optional

import requests

logger = logging.getLogger("determined_tpu.api")


class APIError(Exception):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message


class NotFoundError(APIError):
    pass


class TlsAdapter(requests.adapters.HTTPAdapter):
    """HTTPS adapter pinned to a CA bundle via an explicit ssl_context.

    ``session.verify = cafile`` alone is unreliable on this requests
    version: its pooled-TLS-context cache drops the custom CA on
    connection reuse, so the SECOND request to a self-signed master fails
    verification.  An adapter-owned ``ssl_context`` is applied to every
    connection the pool makes.
    """

    def __init__(self, cafile: str, **kwargs) -> None:
        import ssl

        self._ctx = ssl.create_default_context(cafile=cafile)
        super().__init__(**kwargs)

    def init_poolmanager(self, *args, **kwargs):
        kwargs["ssl_context"] = self._ctx
        return super().init_poolmanager(*args, **kwargs)


class Session:
    RETRIES = 5
    BACKOFF = 0.5

    def __init__(
        self,
        master_url: str,
        token: Optional[str] = None,
        cert_path: Optional[str] = None,
        timeout: float = 30.0,
    ) -> None:
        self.master_url = master_url.rstrip("/")
        self.token = token
        self.timeout = timeout
        self._http = requests.Session()
        # master cert bundle for https:// masters (reference certs.py):
        # explicit arg wins, then the env the agent injects into trials
        if cert_path is None:
            import os

            cert_path = os.environ.get("DTPU_MASTER_CERT") or None
        if cert_path:
            self._http.verify = cert_path
            self._http.mount("https://", TlsAdapter(cert_path))

    def _headers(self) -> Dict[str, str]:
        h = {"Content-Type": "application/json"}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def request(
        self,
        method: str,
        path: str,
        json: Optional[Any] = None,
        params: Optional[Dict[str, Any]] = None,
        stream: bool = False,
        timeout: Optional[float] = None,
    ) -> requests.Response:
        url = self.master_url + (path if path.startswith("/") else "/" + path)
        last: Optional[Exception] = None
        for attempt in range(self.RETRIES):
            try:
                resp = self._http.request(
                    method,
                    url,
                    json=json,
                    params=params,
                    headers=self._headers(),
                    timeout=timeout or self.timeout,
                    stream=stream,
                )
            except requests.ConnectionError as e:
                last = e
                if attempt < self.RETRIES - 1:
                    time.sleep(self.BACKOFF * (2**attempt))
                continue
            if resp.status_code == 404:
                raise NotFoundError(404, resp.text)
            if resp.status_code >= 500:
                last = APIError(resp.status_code, resp.text)
                if attempt < self.RETRIES - 1:
                    time.sleep(self.BACKOFF * (2**attempt))
                continue
            if resp.status_code >= 400:
                raise APIError(resp.status_code, resp.text)
            return resp
        raise last if last is not None else APIError(0, "request failed")

    def get(self, path: str, **kw) -> requests.Response:
        return self.request("GET", path, **kw)

    def post(self, path: str, **kw) -> requests.Response:
        return self.request("POST", path, **kw)

    def patch(self, path: str, **kw) -> requests.Response:
        return self.request("PATCH", path, **kw)

    def put(self, path: str, **kw) -> requests.Response:
        return self.request("PUT", path, **kw)

    def delete(self, path: str, **kw) -> requests.Response:
        return self.request("DELETE", path, **kw)


def login(master_url: str, username: str = "determined", password: str = "") -> Session:
    """Authenticate and return a token-carrying Session."""
    s = Session(master_url)
    resp = s.post("/api/v1/auth/login", json={"username": username, "password": password})
    token = resp.json().get("token")
    return Session(master_url, token=token)
