"""HTTP session to the master (reference: ``common/api/_session.py``).

requests-based with bounded retries, bearer-token auth, and base-url
joining.  This is the single transport used by the Core API contexts, the
SDK, and the CLI.
"""

from __future__ import annotations

import logging
import random
import time
from typing import Any, Dict, Optional

import requests

from determined_tpu.utils import faults

logger = logging.getLogger("determined_tpu.api")

# Methods safe to send twice when the first attempt's fate is unknown.
# POST is excluded by default — a duplicated POST can double-create — and
# must opt in per call site (``retry=True``) when the endpoint is known
# idempotent (e.g. checkpoint reports keyed by uuid).
IDEMPOTENT_METHODS = frozenset({"GET", "HEAD", "PUT", "DELETE", "OPTIONS"})


class APIError(Exception):
    def __init__(
        self, status: int, message: str, retry_after: Optional[str] = None
    ) -> None:
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message
        #: the server's Retry-After header (seconds form), when one came
        #: back on a 429/503 — callers running their own retry loop (the
        #: replica heartbeat) honor it over their computed backoff
        self.retry_after = retry_after


class NotFoundError(APIError):
    pass


class TlsAdapter(requests.adapters.HTTPAdapter):
    """HTTPS adapter pinned to a CA bundle via an explicit ssl_context.

    ``session.verify = cafile`` alone is unreliable on this requests
    version: its pooled-TLS-context cache drops the custom CA on
    connection reuse, so the SECOND request to a self-signed master fails
    verification.  An adapter-owned ``ssl_context`` is applied to every
    connection the pool makes.
    """

    def __init__(self, cafile: str, **kwargs) -> None:
        import ssl

        self._ctx = ssl.create_default_context(cafile=cafile)
        super().__init__(**kwargs)

    def init_poolmanager(self, *args, **kwargs):
        kwargs["ssl_context"] = self._ctx
        return super().init_poolmanager(*args, **kwargs)


class Session:
    RETRIES = 5
    BACKOFF = 0.5

    def __init__(
        self,
        master_url: str,
        token: Optional[str] = None,
        cert_path: Optional[str] = None,
        timeout: float = 30.0,
    ) -> None:
        self.master_url = master_url.rstrip("/")
        self.token = token
        self.timeout = timeout
        self._http = requests.Session()
        # master cert bundle for https:// masters (reference certs.py):
        # explicit arg wins, then the env the agent injects into trials
        if cert_path is None:
            import os

            cert_path = os.environ.get("DTPU_MASTER_CERT") or None
        if cert_path:
            self._http.verify = cert_path
            self._http.mount("https://", TlsAdapter(cert_path))

    def _headers(self) -> Dict[str, str]:
        h = {"Content-Type": "application/json"}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def _backoff_delay(self, attempt: int, retry_after: Optional[str] = None) -> float:
        """Exponential backoff with +/-50% jitter so a gang of trial
        processes retrying the same master outage doesn't stampede in
        lockstep; an explicit ``Retry-After`` (seconds form) wins."""
        if retry_after:
            try:
                return max(float(retry_after), 0.0)
            except ValueError:
                pass  # HTTP-date form: fall through to backoff
        return self.BACKOFF * (2**attempt) * random.uniform(0.5, 1.5)

    def request(
        self,
        method: str,
        path: str,
        json: Optional[Any] = None,
        params: Optional[Dict[str, Any]] = None,
        stream: bool = False,
        timeout: Optional[float] = None,
        retry: Optional[bool] = None,
    ) -> requests.Response:
        """One master request with bounded retries.

        Only idempotent methods retry by default; ``retry`` overrides in
        either direction (a POST to an idempotent endpoint may opt in, a
        GET that must not repeat may opt out).  429 responses are retried
        for every method — rate-limited requests were not executed — and
        429/503 honor the server's ``Retry-After``.
        """
        url = self.master_url + (path if path.startswith("/") else "/" + path)
        retryable = retry if retry is not None else method.upper() in IDEMPOTENT_METHODS
        attempts = self.RETRIES if retryable else 1
        last: Optional[Exception] = None
        attempt = 0
        rate_limited = 0  # 429s retry for every method, on their own counter
        while attempt < attempts:
            try:
                # inside the try so an injected ConnectionError exercises
                # the same retry machinery the real fault would
                faults.fire("api.request", method=method, path=path, attempt=attempt)
                resp = self._http.request(
                    method,
                    url,
                    json=json,
                    params=params,
                    headers=self._headers(),
                    timeout=timeout or self.timeout,
                    stream=stream,
                )
            except (requests.ConnectionError, requests.Timeout) as e:
                # Timeout rides the same path: a read timeout is the classic
                # symptom of a master dying mid-response (SIGKILL during a
                # long-poll), and for idempotent/opted-in requests a retry
                # is exactly what the restarted master expects.
                last = e
                attempt += 1
                if attempt < attempts:
                    time.sleep(self._backoff_delay(attempt - 1))
                continue
            if resp.status_code == 404:
                raise NotFoundError(404, resp.text)
            if resp.status_code == 429:
                # not executed server-side: safe to retry any method —
                # unless the caller explicitly opted out of all retries
                last = APIError(429, resp.text, resp.headers.get("Retry-After"))
                if retry is False:
                    raise last
                rate_limited += 1
                if rate_limited >= self.RETRIES:
                    raise last
                time.sleep(
                    self._backoff_delay(rate_limited - 1, resp.headers.get("Retry-After"))
                )
                continue
            if resp.status_code >= 500:
                last = APIError(resp.status_code, resp.text)
                attempt += 1
                if attempt < attempts:
                    retry_after = (
                        resp.headers.get("Retry-After")
                        if resp.status_code == 503
                        else None
                    )
                    time.sleep(self._backoff_delay(attempt - 1, retry_after))
                continue
            if resp.status_code >= 400:
                raise APIError(resp.status_code, resp.text)
            return resp
        raise last if last is not None else APIError(0, "request failed")

    def get(self, path: str, **kw) -> requests.Response:
        return self.request("GET", path, **kw)

    def post(self, path: str, **kw) -> requests.Response:
        return self.request("POST", path, **kw)

    def patch(self, path: str, **kw) -> requests.Response:
        return self.request("PATCH", path, **kw)

    def put(self, path: str, **kw) -> requests.Response:
        return self.request("PUT", path, **kw)

    def delete(self, path: str, **kw) -> requests.Response:
        return self.request("DELETE", path, **kw)


def login(master_url: str, username: str = "determined", password: str = "") -> Session:
    """Authenticate and return a token-carrying Session.  Login is safe to
    repeat (each attempt just mints a token), so the POST opts into
    retries — masters are commonly still coming up when clients connect."""
    s = Session(master_url)
    resp = s.post(
        "/api/v1/auth/login",
        json={"username": username, "password": password},
        retry=True,
    )
    token = resp.json().get("token")
    return Session(master_url, token=token)
