"""Token store + session bootstrap for the CLI/SDK.

Reference: ``harness/determined/common/api/authentication.py`` — the ``det``
CLI keeps a per-master token cache under ``~/.determined/auth.json`` and
auto-logs-in as the default ``determined`` user (blank password) when no
credentials are supplied.  Same contract here: resolution order is

1. ``DTPU_TOKEN`` env (explicit override),
2. ``DTPU_SESSION_TOKEN`` env (on-cluster: injected by the master into the
   task environment),
3. cached token for this master url (``~/.dtpu/auth.json``, override path
   via ``DTPU_AUTH_PATH``), validated against the master,
4. fresh login with the given (or default) username/password, cached.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

from determined_tpu.api.session import APIError, Session

DEFAULT_USER = "determined"


def _auth_path() -> str:
    return os.environ.get(
        "DTPU_AUTH_PATH", os.path.join(os.path.expanduser("~"), ".dtpu", "auth.json")
    )


class TokenStore:
    """Per-master-url token cache on disk."""

    def __init__(self, path: Optional[str] = None) -> None:
        self.path = path or _auth_path()

    def _load(self) -> Dict[str, Dict[str, str]]:
        try:
            with open(self.path) as f:
                data = json.load(f)
            return data if isinstance(data, dict) else {}
        except (OSError, ValueError):
            return {}

    def get(self, master_url: str) -> Optional[Dict[str, str]]:
        return self._load().get(master_url.rstrip("/"))

    def set(self, master_url: str, username: str, token: str) -> None:
        data = self._load()
        data[master_url.rstrip("/")] = {"username": username, "token": token}
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=2)
        os.chmod(tmp, 0o600)
        os.replace(tmp, self.path)

    def clear(self, master_url: str) -> None:
        data = self._load()
        if data.pop(master_url.rstrip("/"), None) is not None:
            with open(self.path, "w") as f:
                json.dump(data, f, indent=2)


def login(
    master_url: str,
    username: str = DEFAULT_USER,
    password: str = "",
    store: Optional[TokenStore] = None,
) -> Session:
    """Authenticate, cache the token, and return a token-carrying Session."""
    anon = Session(master_url)
    resp = anon.post(
        "/api/v1/auth/login", json={"username": username, "password": password}
    )
    token = resp.json()["token"]
    (store or TokenStore()).set(master_url, username, token)
    return Session(master_url, token=token)


def _token_valid(master_url: str, token: str) -> bool:
    try:
        Session(master_url, token=token).get("/api/v1/users")
        return True
    except APIError:
        return False


def ensure_session(
    master_url: str,
    username: Optional[str] = None,
    password: Optional[str] = None,
) -> Session:
    """Return an authenticated Session using the resolution order above.

    A ``username`` without a ``password`` still prefers that user's cached
    token (so ``dtpu -u alice ...`` works after ``dtpu login -u alice``);
    an explicit password always re-authenticates.
    """
    env_token = os.environ.get("DTPU_TOKEN") or os.environ.get("DTPU_SESSION_TOKEN")
    if env_token:
        return Session(master_url, token=env_token)
    store = TokenStore()
    if password is None:
        cached = store.get(master_url)
        if (
            cached
            and (username is None or cached.get("username") == username)
            and _token_valid(master_url, cached["token"])
        ):
            return Session(master_url, token=cached["token"])
    return login(master_url, username or DEFAULT_USER, password or "", store)
