from determined_tpu.api.session import Session, login, APIError, NotFoundError  # noqa: F401
