"""Declarative API contract: every master route, in one table.

Reference: the proto/swagger contract (``proto/src/determined/**`` →
generated ``bindings.py``) that keeps client and server from drifting.
This build's master is hand-rolled C++, so the contract lives here as
data: the SDK/CLI call through it conceptually, and
``tests/test_api_contract.py`` drives EVERY route against a live
devcluster asserting status + response shape — the drift a generated
client would catch at codegen time is caught in CI instead (the round-2
``alert()``-404 class of bug).

Each entry: method, path template, auth level, and the top-level keys a
successful JSON response must contain ("[]" = JSON array response,
``None`` = shape not asserted, e.g. text).
"""

from __future__ import annotations

API_VERSION = 1

# (method, path, auth, response_keys)
ROUTES = [
    # auth + users
    ("POST", "/api/v1/auth/login", "anon", {"token", "username", "admin"}),
    ("GET", "/api/v1/auth/whoami", "token", {"username", "admin"}),
    ("POST", "/api/v1/users", "admin", {"created"}),
    ("GET", "/api/v1/users", "token", "[]"),
    # master info + observability
    ("GET", "/api/v1/master", "anon", {"version", "cluster_name", "agents"}),
    ("GET", "/metrics", "anon", None),
    # experiments
    ("POST", "/api/v1/experiments", "token", {"id"}),
    ("GET", "/api/v1/experiments", "token", "[]"),
    ("GET", "/api/v1/experiments/{id}", "token",
     {"id", "name", "owner", "state", "config", "progress", "trials"}),
    ("GET", "/api/v1/experiments/{id}/context", "token", None),
    ("GET", "/api/v1/workspaces", "token", "[]"),
    # first-class workspace entities + scoped RBAC
    ("POST", "/api/v1/workspaces", "token", {"name", "owner"}),
    ("POST", "/api/v1/workspaces/{name}/archive", "token", {"name", "archived"}),
    ("POST", "/api/v1/workspaces/{name}/unarchive", "token", {"name", "archived"}),
    ("PUT", "/api/v1/workspaces/{name}/roles", "token", {"name", "username", "role"}),
    # first-class projects (workspace -> project -> experiment hierarchy)
    ("POST", "/api/v1/workspaces/{name}/projects", "token",
     {"name", "workspace", "owner"}),
    ("GET", "/api/v1/workspaces/{name}/projects", "token", "[]"),
    ("PATCH", "/api/v1/projects/{ws}/{project}", "token",
     {"name", "description", "notes"}),
    ("POST", "/api/v1/projects/{ws}/{project}/archive", "token",
     {"name", "archived"}),
    ("POST", "/api/v1/projects/{ws}/{project}/unarchive", "token",
     {"name", "archived"}),
    ("POST", "/api/v1/experiments/{id}/move", "token",
     {"id", "workspace", "project"}),
    ("DELETE", "/api/v1/projects/{ws}/{project}", "token", set()),
    ("DELETE", "/api/v1/workspaces/{name}", "token", set()),
    # user groups (role bindings may target groups; members inherit)
    ("POST", "/api/v1/groups", "token", {"name"}),
    ("GET", "/api/v1/groups", "token", "[]"),
    ("POST", "/api/v1/groups/{group}/members", "token", {"name", "username"}),
    ("DELETE", "/api/v1/groups/{group}/members/{username}", "token", set()),
    ("DELETE", "/api/v1/groups/{group}", "token", set()),
    # named access tokens (secret shown once; list/revoke by id)
    ("POST", "/api/v1/tokens", "token", {"id", "name", "username", "token"}),
    ("GET", "/api/v1/tokens", "token", "[]"),
    ("DELETE", "/api/v1/tokens/{token_id}", "token", set()),
    ("POST", "/api/v1/experiments/{id}/fork", "token", {"id", "forked_from"}),
    ("POST", "/api/v1/experiments/{id}/continue", "token",
     {"id", "forked_from", "continued_from_checkpoint"}),
    # driver-managed searcher surface (harness-side search loop)
    ("POST", "/api/v1/experiments/{id}/trials", "token", {"id"}),
    ("POST", "/api/v1/experiments/{id}/searcher/shutdown", "token", {"state"}),
    ("POST", "/api/v1/trials/{id}/stop", "token", {"state", "stop_requested"}),
    ("POST", "/api/v1/experiments/{id}/pause", "token", {"state"}),
    ("POST", "/api/v1/experiments/{id}/activate", "token", {"state"}),
    ("POST", "/api/v1/experiments/{id}/cancel", "token", {"state"}),
    ("POST", "/api/v1/experiments/{id}/kill", "token", {"state"}),
    ("DELETE", "/api/v1/experiments/{id}", "token", set()),
    # trials
    ("GET", "/api/v1/trials/{id}", "token",
     {"id", "experiment_id", "state", "restarts", "latest_checkpoint",
      "allocation_id", "progress"}),
    ("POST", "/api/v1/trials/{id}/progress", "token", set()),
    ("POST", "/api/v1/trials/{id}/heartbeat", "token", set()),
    ("POST", "/api/v1/trials/{id}/exit", "token", set()),
    ("GET", "/api/v1/trials/{id}/metrics", "token", "[]"),
    ("GET", "/api/v1/trials/{id}/logs", "token", "[]"),
    ("POST", "/api/v1/metrics", "token", set()),
    ("POST", "/api/v1/trials/metrics", "token", set()),
    ("POST", "/api/v1/logs", "token", set()),
    # checkpoints + models
    ("POST", "/api/v1/checkpoints", "token", set()),
    ("GET", "/api/v1/checkpoints", "token", "[]"),
    ("GET", "/api/v1/checkpoints/{uuid}", "token", {"uuid"}),
    ("DELETE", "/api/v1/checkpoints/{uuid}", "token", set()),
    ("POST", "/api/v1/models", "token", {"name"}),
    ("GET", "/api/v1/models", "token", "[]"),
    ("GET", "/api/v1/models/{name}", "token", {"name", "versions"}),
    ("POST", "/api/v1/models/{name}/versions", "token", {"version"}),
    ("GET", "/api/v1/models/{name}/versions", "token", "[]"),
    ("GET", "/api/v1/models/{name}/versions/{version}", "token",
     {"version", "checkpoint_uuid", "storage_path", "model"}),
    ("POST", "/api/v1/models/{name}/promote", "token",
     {"version", "checkpoint_uuid"}),
    # serving fleet: rolling deployment of a registry version
    ("POST", "/api/v1/serving/deploy", "token",
     {"id", "model", "version", "target", "status"}),
    ("GET", "/api/v1/serving/deploy", "token",
     {"id", "model", "version", "target", "status"}),
    # supervised fleet spec: master relaunches dead replicas to hold target
    ("PUT", "/api/v1/serving/fleet", "token",
     {"model", "version", "target", "status", "slots"}),
    ("GET", "/api/v1/serving/fleet", "token",
     {"model", "version", "target", "status", "slots"}),
    # serving data plane: replica registry + master-routed generation
    ("POST", "/api/v1/serving/replicas", "token", {"id", "heartbeat_ttl_ms"}),
    ("POST", "/api/v1/serving/replicas/{id}/heartbeat", "token", set()),
    ("DELETE", "/api/v1/serving/replicas/{id}", "token", set()),
    ("GET", "/api/v1/serving", "token", "[]"),
    ("POST", "/v1/generate", "token", None),
    # agents + scheduling
    ("POST", "/api/v1/agents", "token", {"registered"}),
    ("GET", "/api/v1/agents", "token", "[]"),
    ("GET", "/api/v1/agents/{id}/work", "token", "[]"),
    ("GET", "/api/v1/resource-pools", "token", "[]"),
    ("GET", "/api/v1/job-queue", "token", "[]"),
    # allocations
    ("GET", "/api/v1/allocations/{id}/signals/preemption", "token", {"preempt"}),
    ("POST", "/api/v1/allocations/{id}/signals/ack_preemption", "token", set()),
    # webhooks
    ("POST", "/api/v1/webhooks", "token", {"id", "name"}),
    ("GET", "/api/v1/webhooks", "token", "[]"),
    ("DELETE", "/api/v1/webhooks/{id}", "token", set()),
    ("POST", "/api/v1/webhooks/custom", "token", set()),
    # config templates
    ("PUT", "/api/v1/templates/{name}", "token", {"name"}),
    ("GET", "/api/v1/templates", "token", "[]"),
    ("GET", "/api/v1/templates/{name}", "token", {"name", "config"}),
    ("DELETE", "/api/v1/templates/{name}", "token", set()),
    # config policies (cluster/workspace defaults + invariants + constraints)
    ("PUT", "/api/v1/config-policies/{scope}", "admin", {"scope"}),
    ("GET", "/api/v1/config-policies", "token", "[]"),
    ("GET", "/api/v1/config-policies/{scope}", "token", {"scope", "policy"}),
    ("DELETE", "/api/v1/config-policies/{scope}", "admin", set()),
    # events (streaming updates)
    ("GET", "/api/v1/events", "token", "[]"),
    # generic tasks + proxy
    ("POST", "/api/v1/tasks", "token", {"id", "type", "state", "proxy_url"}),
    ("GET", "/api/v1/tasks", "token", "[]"),
    ("GET", "/api/v1/tasks/{id}", "token",
     {"id", "type", "owner", "state", "ready", "agent_id", "proxy_url"}),
    ("POST", "/api/v1/tasks/{id}/ready", "token", set()),
    ("POST", "/api/v1/tasks/{id}/exit", "token", set()),
    ("DELETE", "/api/v1/tasks/{id}", "token", set()),
    ("GET", "/api/v1/tasks/{id}/logs", "token", "[]"),
    ("GET", "/proxy/{id}/{path}", "token", None),
]


def markdown() -> str:
    """Render the contract as API.md content."""
    out = [
        "# Master REST API (contract v%d)\n" % API_VERSION,
        "Generated from `determined_tpu/api/spec.py`; "
        "`tests/test_api_contract.py` asserts every row against a live "
        "master, and `dtpu lint --native` cross-references this table "
        "against the master's actual `srv.route` dispatch "
        "([docs/lint.md](docs/lint.md#control-plane-contract)).\n",
        "| method | path | auth | response |",
        "|---|---|---|---|",
    ]
    for method, path, auth, keys in ROUTES:
        if keys == "[]":
            resp = "array"
        elif keys is None:
            resp = "raw"
        elif keys:
            resp = "{" + ", ".join(sorted(keys)) + "}"
        else:
            resp = "{}"
        out.append(f"| {method} | `{path}` | {auth} | {resp} |")
    return "\n".join(out) + "\n"
