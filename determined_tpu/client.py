"""Python SDK: ORM-ish client objects over the master REST API.

Reference: ``harness/determined/experimental/client.py:107-623`` —
``Determined`` entry object with ``create_experiment`` / ``get_experiment``
/ ``get_trial`` / checkpoint + model registry objects, and module-level
convenience functions bound to a default client.  The CLI is built on this
SDK, so every CLI verb is scriptable.

Usage::

    from determined_tpu import client
    d = client.Determined("http://master:8080")
    exp = d.create_experiment("exp.yaml", context_dir="./model")
    exp.wait()
    best = exp.best_trial(metric="validation_accuracy", smaller_is_better=False)
"""

from __future__ import annotations

import base64
import os
import time
from typing import Any, Dict, Iterator, List, Optional, Union

from determined_tpu.api.authentication import ensure_session, login as _auth_login
from determined_tpu.api.session import Session

TERMINAL_STATES = ("COMPLETED", "CANCELED", "ERROR")


class _Resource:
    """Base for API-backed objects: a Session + a raw dict snapshot."""

    def __init__(self, session: Session, data: Dict[str, Any]) -> None:
        self._session = session
        self._data = dict(data)

    def __getitem__(self, key: str) -> Any:
        return self._data[key]

    def get(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._data)


class Experiment(_Resource):
    @property
    def id(self) -> int:
        return int(self._data["id"])

    @property
    def state(self) -> str:
        return self._data["state"]

    @property
    def progress(self) -> float:
        return float(self._data.get("progress", 0.0))

    @property
    def config(self) -> Dict[str, Any]:
        return self._data.get("config") or {}

    def reload(self) -> "Experiment":
        self._data = self._session.get(f"/api/v1/experiments/{self.id}").json()
        return self

    def _signal(self, verb: str) -> "Experiment":
        self._session.post(f"/api/v1/experiments/{self.id}/{verb}")
        return self.reload()

    def pause(self) -> "Experiment":
        return self._signal("pause")

    def activate(self) -> "Experiment":
        return self._signal("activate")

    def cancel(self) -> "Experiment":
        return self._signal("cancel")

    def kill(self) -> "Experiment":
        return self._signal("kill")

    def fork(self, config_overrides: Optional[Dict[str, Any]] = None) -> "Experiment":
        """New experiment from this one's config (+ overrides); inherits the
        context directory, starts from scratch."""
        resp = self._session.post(
            f"/api/v1/experiments/{self.id}/fork",
            json={"config": config_overrides or {}},
        )
        return Experiment(self._session, resp.json()).reload()

    def continue_(self, config_overrides: Optional[Dict[str, Any]] = None) -> "Experiment":
        """Fork whose trials resume from this experiment's newest
        checkpoint (reference handleContinueExperiment)."""
        resp = self._session.post(
            f"/api/v1/experiments/{self.id}/continue",
            json={"config": config_overrides or {}},
        )
        return Experiment(self._session, resp.json()).reload()

    def delete(self) -> None:
        """Delete this terminal experiment: records removed, checkpoints
        and profiler traces GC'd from storage (reference: det experiment
        delete)."""
        self._session.delete(f"/api/v1/experiments/{self.id}")

    def wait(self, timeout: Optional[float] = None, interval: float = 1.0) -> str:
        """Poll until the experiment reaches a terminal state; returns it."""
        deadline = None if timeout is None else time.time() + timeout
        while True:
            self.reload()
            if self.state in TERMINAL_STATES:
                return self.state
            if deadline is not None and time.time() > deadline:
                raise TimeoutError(
                    f"experiment {self.id} still {self.state} after {timeout}s"
                )
            time.sleep(interval)

    def get_trials(self) -> List["Trial"]:
        self.reload()
        return [
            Trial(self._session, t if isinstance(t, dict) else {"id": t})
            for t in self._data.get("trials", [])
        ]

    def best_trial(
        self, metric: Optional[str] = None, smaller_is_better: Optional[bool] = None
    ) -> Optional["Trial"]:
        """Trial with the best reported searcher metric (reference:
        client.py Experiment top_checkpoint / ordering semantics)."""
        scfg = (self.config.get("searcher") or {})
        metric = metric or scfg.get("metric", "loss")
        if smaller_is_better is None:
            smaller_is_better = bool(scfg.get("smaller_is_better", True))
        best, best_val = None, None
        for t in self.get_trials():
            val = t.reload().summary_metric(metric)
            if val is None:
                continue
            if (
                best_val is None
                or (smaller_is_better and val < best_val)
                or (not smaller_is_better and val > best_val)
            ):
                best, best_val = t, val
        return best


class Trial(_Resource):
    @property
    def id(self) -> int:
        return int(self._data["id"])

    @property
    def state(self) -> str:
        return self._data.get("state", "")

    def reload(self) -> "Trial":
        self._data = self._session.get(f"/api/v1/trials/{self.id}").json()
        return self

    def iter_metrics(self, group: Optional[str] = None) -> Iterator[Dict[str, Any]]:
        """Yield reported metric records, oldest first (reference:
        client.py Trial.iter_metrics / stream_trials_metrics)."""
        params = {"group": group} if group else None
        rows = self._session.get(
            f"/api/v1/trials/{self.id}/metrics", params=params
        ).json()
        yield from rows

    def summary_metric(self, name: str, group: str = "validation") -> Optional[float]:
        """Latest reported value of one validation metric."""
        last = None
        for row in self.iter_metrics(group=group):
            metrics = row.get("metrics", row)
            if name in metrics:
                last = metrics[name]
        return None if last is None else float(last)

    def logs(
        self, follow: bool = False, timeout: Optional[float] = None
    ) -> Iterator[str]:
        """Yield log lines; ``follow=True`` streams until the trial leaves
        PENDING/RUNNING (or ``timeout`` seconds elapse, if given)."""
        offset = 0
        deadline = None if timeout is None else time.time() + timeout
        while True:
            lines = self._session.get(
                f"/api/v1/trials/{self.id}/logs", params={"offset": offset}
            ).json()
            yield from lines
            offset += len(lines)
            if not follow:
                return
            self.reload()
            if self.state not in ("PENDING", "RUNNING"):
                return
            if deadline is not None and time.time() > deadline:
                return
            time.sleep(0.5)

    def list_checkpoints(self) -> List["Checkpoint"]:
        # the master's listing iterates a uuid-keyed map (arbitrary order)
        # and keeps gc'd records as state=DELETED tombstones: drop those
        # and sort by steps_completed so [-1] is the newest checkpoint,
        # which gc retention (save_trial_latest) guarantees is on disk
        cps = self._session.get("/api/v1/checkpoints").json()
        mine = [
            c
            for c in cps
            if c.get("trial_id") == self.id and c.get("state") != "DELETED"
        ]
        mine.sort(
            key=lambda c: (
                (c.get("metadata") or {}).get("steps_completed") or 0,
                c.get("uuid") or "",
            )
        )
        return [Checkpoint(self._session, c) for c in mine]


class Checkpoint(_Resource):
    @property
    def uuid(self) -> str:
        return self._data["uuid"]

    @property
    def trial_id(self) -> Optional[int]:
        tid = self._data.get("trial_id")
        return None if tid is None else int(tid)

    @property
    def metadata(self) -> Dict[str, Any]:
        return self._data.get("metadata") or {}

    def reload(self) -> "Checkpoint":
        self._data = self._session.get(f"/api/v1/checkpoints/{self.uuid}").json()
        return self

    def delete(self) -> None:
        self._session.delete(f"/api/v1/checkpoints/{self.uuid}")

    def download(self, target_dir: Optional[str] = None) -> str:
        """Fetch the checkpoint's files locally via the owning experiment's
        storage config; returns the local directory (reference:
        ``Checkpoint.download``).  Pair with
        ``train.load_trial_from_checkpoint`` to rebuild the model."""
        if self.trial_id is None:
            raise ValueError("checkpoint has no trial; cannot resolve storage")
        trial = self._session.get(f"/api/v1/trials/{self.trial_id}").json()
        exp = self._session.get(
            f"/api/v1/experiments/{trial['experiment_id']}"
        ).json()
        storage_raw = (exp.get("config") or {}).get("checkpoint_storage")
        if not storage_raw:
            raise ValueError("experiment config has no checkpoint_storage")
        from determined_tpu.storage import from_expconf

        storage = from_expconf(storage_raw)
        import tempfile

        target = target_dir or tempfile.mkdtemp(prefix=f"dtpu-ckpt-{self.uuid}-")
        storage.download(self.uuid, target)
        return target


class ModelVersion(_Resource):
    @property
    def version(self) -> int:
        return int(self._data["version"])

    @property
    def checkpoint_uuid(self) -> str:
        return self._data.get("checkpoint_uuid", "")

    @property
    def storage_path(self) -> str:
        return self._data.get("storage_path", "")


class Model(_Resource):
    @property
    def name(self) -> str:
        return self._data["name"]

    def reload(self) -> "Model":
        self._data = self._session.get(f"/api/v1/models/{self.name}").json()
        return self

    def register_version(
        self, checkpoint_uuid: str, metadata: Optional[Dict[str, Any]] = None
    ) -> ModelVersion:
        resp = self._session.post(
            f"/api/v1/models/{self.name}/versions",
            json={"checkpoint_uuid": checkpoint_uuid, "metadata": metadata or {}},
        )
        return ModelVersion(self._session, resp.json())

    def get_versions(self) -> List[ModelVersion]:
        rows = self._session.get(f"/api/v1/models/{self.name}/versions").json()
        return [ModelVersion(self._session, r) for r in rows]


class Determined:
    """SDK entry point (reference: ``determined.experimental.Determined``)."""

    def __init__(
        self,
        master: Optional[str] = None,
        user: Optional[str] = None,
        password: Optional[str] = None,
        session: Optional[Session] = None,
    ) -> None:
        self.master = (
            master or os.environ.get("DTPU_MASTER") or "http://127.0.0.1:8080"
        )
        self._session = session or ensure_session(self.master, user, password)

    @property
    def session(self) -> Session:
        return self._session

    # -- experiments --
    def create_experiment(
        self,
        config: Union[str, Dict[str, Any]],
        context_dir: Optional[str] = None,
        context_bytes: Optional[bytes] = None,
        template: Optional[str] = None,
    ) -> Experiment:
        """Submit an experiment; ``config`` is a yaml path or a dict.
        ``context_dir`` is packed (honoring .detignore) and shipped;
        pass ``context_bytes`` instead if you already packed it.
        ``template`` names a master-stored config template the config is
        merged over (config wins; reference templates/)."""
        if isinstance(config, str):
            import yaml

            with open(config) as f:
                config = yaml.safe_load(f)
        from determined_tpu.config.experiment import ExperimentConfig

        if template is None:
            ExperimentConfig.parse(dict(config))  # client-side validation
        body: Dict[str, Any] = {"config": config}
        if template is not None:
            body["template"] = template
        if context_bytes is None and context_dir:
            from determined_tpu.common import build_context

            context_bytes = build_context(context_dir)
        if context_bytes is not None:
            body["context"] = base64.b64encode(context_bytes).decode()
        resp = self._session.post("/api/v1/experiments", json=body)
        return Experiment(self._session, resp.json())

    def get_experiment(self, experiment_id: int) -> Experiment:
        return Experiment(
            self._session,
            self._session.get(f"/api/v1/experiments/{experiment_id}").json(),
        )

    def list_experiments(
        self,
        workspace: Optional[str] = None,
        project: Optional[str] = None,
        owner: Optional[str] = None,
    ) -> List[Experiment]:
        params = {
            k: v
            for k, v in {
                "workspace": workspace, "project": project, "owner": owner
            }.items()
            if v is not None
        }
        rows = self._session.get("/api/v1/experiments", params=params or None).json()
        return [Experiment(self._session, r) for r in rows]

    def list_workspaces(self) -> List[Dict[str, Any]]:
        """Workspace/project tree with experiment counts."""
        return self._session.get("/api/v1/workspaces").json()

    # -- trials / checkpoints --
    def get_trial(self, trial_id: int) -> Trial:
        return Trial(
            self._session, self._session.get(f"/api/v1/trials/{trial_id}").json()
        )

    def get_checkpoint(self, uuid: str) -> Checkpoint:
        return Checkpoint(
            self._session, self._session.get(f"/api/v1/checkpoints/{uuid}").json()
        )

    def list_checkpoints(self) -> List[Checkpoint]:
        rows = self._session.get("/api/v1/checkpoints").json()
        return [Checkpoint(self._session, r) for r in rows]

    # -- model registry --
    def create_model(
        self, name: str, description: str = "", metadata: Optional[Dict] = None
    ) -> Model:
        resp = self._session.post(
            "/api/v1/models",
            json={"name": name, "description": description, "metadata": metadata or {}},
        )
        return Model(self._session, resp.json())

    def get_model(self, name: str) -> Model:
        return Model(self._session, self._session.get(f"/api/v1/models/{name}").json())

    def get_models(self) -> List[Model]:
        rows = self._session.get("/api/v1/models").json()
        return [Model(self._session, r) for r in rows]

    def resolve_model_version(self, ref: str) -> ModelVersion:
        """Resolve ``name[@version|@latest]`` to its registered version
        (checkpoint uuid + storage path + lineage)."""
        from determined_tpu.experiment.registry import resolve_version

        return ModelVersion(self._session, resolve_version(self._session, ref))

    def deploy_model(
        self,
        ref: str,
        *,
        canary_fraction: Optional[float] = None,
        rollback_on_regression: bool = False,
        bake_seconds: Optional[float] = None,
        min_requests: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Start a rolling deployment of a registry version onto the
        serving fleet; returns the deploy state (poll
        ``get_serving_deploy`` until ``status != "rolling"``).

        With ``canary_fraction`` the master rolls only that cohort first,
        bakes it for ``bake_seconds`` comparing error rate and latency
        against the pre-roll baseline, and either finishes the roll or
        holds (``rollback_on_regression=True`` rolls the cohort back to
        the prior version instead of holding)."""
        from determined_tpu.experiment.registry import parse_model_ref

        name, version = parse_model_ref(ref)
        body: Dict[str, Any] = {"model": name, "version": version}
        if canary_fraction is not None:
            body["canary_fraction"] = float(canary_fraction)
            if bake_seconds is not None:
                body["bake_seconds"] = float(bake_seconds)
            if min_requests is not None:
                body["min_requests"] = int(min_requests)
            if rollback_on_regression:
                body["rollback_on_regression"] = True
        return self._session.post("/api/v1/serving/deploy", json=body).json()

    def get_serving_deploy(self) -> Dict[str, Any]:
        return self._session.get("/api/v1/serving/deploy").json()

    def set_serving_fleet(
        self,
        ref: str,
        target: int,
        *,
        pool: Optional[str] = None,
        config: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Declare the serving-fleet spec: ``target`` replicas of registry
        version ``ref`` (``name@version``).  The master's supervisor
        launches replicas as agent tasks and relaunches any that die
        (capped backoff; crash loops mark the fleet degraded).  ``config``
        merges into each replica's task config (``resources.slots``,
        ``serve`` overrides, ``env``)."""
        from determined_tpu.experiment.registry import parse_model_ref

        name, version = parse_model_ref(ref)
        body: Dict[str, Any] = {
            "model": name,
            "version": version,
            "target": int(target),
        }
        if pool:
            body["pool"] = pool
        if config:
            body["config"] = config
        return self._session.put("/api/v1/serving/fleet", json=body).json()

    def get_serving_fleet(self) -> Dict[str, Any]:
        """The supervised fleet's spec + per-slot status (404 when no
        fleet spec has been declared)."""
        return self._session.get("/api/v1/serving/fleet").json()

    def get_serving(self) -> List[Dict[str, Any]]:
        """The live serving-replica routing table."""
        return self._session.get("/api/v1/serving").json()

    def generate(
        self,
        prompt_tokens: List[int],
        *,
        max_new_tokens: Optional[int] = None,
        session_key: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Generate through the master's router (``POST /v1/generate``):
        the master picks the least-loaded replica with consistent-hash
        affinity on ``session_key`` (or the prompt prefix), so repeated
        calls with the same key land on the replica holding the prefix
        cache.  Raises on 503 (no live replica / fleet saturated) like
        every other binding — callers retry with backoff."""
        body: Dict[str, Any] = {"prompt_tokens": list(prompt_tokens)}
        if max_new_tokens is not None:
            body["max_new_tokens"] = int(max_new_tokens)
        if session_key is not None:
            body["session"] = session_key
        return self._session.post("/v1/generate", json=body).json()

    # -- generic tasks (NTSC: tensorboard viewer behind the proxy) --
    def start_tensorboard(
        self, experiment_ids: Optional[List[int]] = None
    ) -> Dict[str, Any]:
        """Launch a tensorboard/metrics-viewer task; returns task info with
        ``proxy_url`` (reference: ``det tensorboard start``)."""
        resp = self._session.post(
            "/api/v1/tasks",
            json={
                "type": "tensorboard",
                "config": {"experiment_ids": experiment_ids or []},
            },
        )
        return resp.json()

    def start_notebook(
        self, work_dir: Optional[str] = None, resource_pool: Optional[str] = None
    ) -> Dict[str, Any]:
        """Launch a Jupyter notebook task behind the proxy (reference:
        ``det notebook start``)."""
        body: Dict[str, Any] = {
            "type": "notebook", "config": {"work_dir": work_dir or ""},
        }
        if resource_pool:
            body["resource_pool"] = resource_pool
        resp = self._session.post("/api/v1/tasks", json=body)
        return resp.json()

    def run_command(
        self,
        entrypoint: Any,
        *,
        resource_pool: Optional[str] = None,
        slots: int = 0,
        env: Optional[Dict[str, str]] = None,
        work_dir: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Run an arbitrary command as a scheduler-placed task (reference:
        ``det cmd run``, ``master/internal/command/command.go``).
        ``entrypoint`` is an argv list or a shell string."""
        config: Dict[str, Any] = {"entrypoint": entrypoint}
        if env:
            config["env"] = dict(env)
        if work_dir:
            config["work_dir"] = work_dir
        if slots:
            config["resources"] = {"slots": int(slots)}
        body: Dict[str, Any] = {"type": "command", "config": config}
        if resource_pool:
            body["resource_pool"] = resource_pool
        return self._session.post("/api/v1/tasks", json=body).json()

    def task_logs(self, task_id: str) -> List[Dict[str, Any]]:
        return self._session.get(f"/api/v1/tasks/{task_id}/logs").json()

    def wait_task_done(self, task_id: str, timeout: float = 120.0) -> Dict[str, Any]:
        """Wait until the task reaches TERMINATED (commands run to
        completion; viewers terminate on kill/idle)."""
        deadline = time.time() + timeout
        while True:
            info = self.get_task(task_id)
            if info.get("state") == "TERMINATED":
                return info
            if time.time() > deadline:
                raise TimeoutError(f"task {task_id} still running after {timeout}s")
            time.sleep(0.5)

    def start_shell(self, shell: Optional[str] = None) -> Dict[str, Any]:
        """Launch a shell task (PTY behind a websocket through the proxy;
        reference: ``det shell start`` + sshd tunnel)."""
        resp = self._session.post(
            "/api/v1/tasks",
            json={"type": "shell", "config": {"shell": shell or "/bin/sh"}},
        )
        return resp.json()

    def open_shell_ws(self, task_id: str):
        """Open the shell task's websocket through the master proxy; returns
        a connected ``determined_tpu.common.ws.WebSocket``.  https masters
        get wss with the session's CA bundle (DTPU_MASTER_CERT / --cert)."""
        import os
        from urllib.parse import urlparse

        from determined_tpu.common import ws as wslib

        u = urlparse(self.master)
        https = u.scheme == "https"
        tls_ca = None
        if https:
            verify = getattr(self._session._http, "verify", None)
            tls_ca = verify if isinstance(verify, str) else os.environ.get(
                "DTPU_MASTER_CERT"
            )
        return wslib.connect(
            u.hostname or "127.0.0.1",
            u.port or (443 if https else 80),
            f"/proxy/{task_id}/ws",
            headers={"Authorization": f"Bearer {self._session.token}"},
            tls_ca=tls_ca,
        )

    def get_task(self, task_id: str) -> Dict[str, Any]:
        return self._session.get(f"/api/v1/tasks/{task_id}").json()

    def list_tasks(self) -> List[Dict[str, Any]]:
        return self._session.get("/api/v1/tasks").json()

    def kill_task(self, task_id: str) -> None:
        self._session.delete(f"/api/v1/tasks/{task_id}")

    def wait_task_ready(self, task_id: str, timeout: float = 60.0) -> Dict[str, Any]:
        deadline = time.time() + timeout
        while True:
            info = self.get_task(task_id)
            if info.get("ready"):
                return info
            if info.get("state") == "TERMINATED":
                raise RuntimeError(f"task {task_id} terminated before ready")
            if time.time() > deadline:
                raise TimeoutError(f"task {task_id} not ready after {timeout}s")
            time.sleep(0.5)

    # -- named access tokens (reference internal/token/) --
    def create_token(
        self, name: str, ttl_days: int = 30, username: Optional[str] = None
    ) -> Dict[str, Any]:
        """Create a named access token.  The returned dict's ``token`` is
        the only time the secret is shown; list/revoke use ``id``."""
        body: Dict[str, Any] = {"name": name, "ttl_days": ttl_days}
        if username:
            body["username"] = username
        return self._session.post("/api/v1/tokens", json=body).json()

    def list_tokens(self) -> List[Dict[str, Any]]:
        return self._session.get("/api/v1/tokens").json()

    def revoke_token(self, token_id: str) -> None:
        self._session.delete(f"/api/v1/tokens/{token_id}")

    # -- workspaces (reference api_project.go + rbac/) --
    def create_workspace(self, name: str) -> Dict[str, Any]:
        return self._session.post("/api/v1/workspaces", json={"name": name}).json()

    def list_workspaces(self) -> List[Dict[str, Any]]:
        return self._session.get("/api/v1/workspaces").json()

    def archive_workspace(self, name: str, archived: bool = True) -> None:
        from urllib.parse import quote

        verb = "archive" if archived else "unarchive"
        self._session.post(f"/api/v1/workspaces/{quote(name, safe='')}/{verb}")

    def delete_workspace(self, name: str) -> None:
        from urllib.parse import quote

        self._session.delete(f"/api/v1/workspaces/{quote(name, safe='')}")

    def assign_workspace_role(self, name: str, username: str, role: str) -> None:
        """Bind ``username`` to ``role`` (viewer/user/admin; "none" removes)
        in workspace ``name``; a workspace with any binding is restricted
        to bound users + its owner + cluster admins."""
        from urllib.parse import quote

        self._session.put(
            f"/api/v1/workspaces/{quote(name, safe='')}/roles",
            json={"username": username, "role": role},
        )

    # -- streaming events (reference common/streams/_client.py) --
    def events(
        self,
        since: int = 0,
        follow: bool = False,
        types: Optional[List[str]] = None,
        poll_timeout: float = 30.0,
    ):
        """Iterate the master's seq-ordered event feed.

        The reference streams entity deltas over a websocket
        (``harness/determined/common/streams/_client.py``); here the
        journal doubles as the feed and a long-poll carries it.  Yields
        event dicts (each has ``seq`` + ``type``); with ``follow=True``
        blocks for new events until the caller breaks, otherwise returns
        once the backlog is drained.
        """
        while True:
            params = {"since": str(since)}
            if follow:
                params["timeout_seconds"] = str(int(poll_timeout))
            batch = self._session.get("/api/v1/events", params=params).json()
            for ev in batch:
                since = max(since, int(ev.get("seq", since)))
                if types and ev.get("type") not in types:
                    continue
                yield ev
            if not batch and not follow:
                return

    # -- config templates --
    def set_template(self, name: str, config: Dict[str, Any]) -> None:
        self._session.put(f"/api/v1/templates/{name}", json={"config": config})

    def get_template(self, name: str) -> Dict[str, Any]:
        return self._session.get(f"/api/v1/templates/{name}").json()["config"]

    def list_templates(self) -> List[Dict[str, Any]]:
        return self._session.get("/api/v1/templates").json()

    def delete_template(self, name: str) -> None:
        self._session.delete(f"/api/v1/templates/{name}")

    # -- streaming updates --
    def stream_events(
        self, since: int = 0, poll_timeout: int = 30
    ) -> Iterator[Dict[str, Any]]:
        """Follow the master's seq-ordered event feed (reference:
        streams client over internal/stream/ websocket deltas; here a
        long-polled journal tail).  Yields events forever; track
        ``event["seq"]`` to resume."""
        while True:
            rows = self._session.get(
                "/api/v1/events",
                params={"since": since, "timeout_seconds": poll_timeout},
                timeout=poll_timeout + 15,
            ).json()
            for ev in rows:
                since = max(since, int(ev.get("seq", 0)))
                yield ev

    def get_events(self, since: int = 0) -> List[Dict[str, Any]]:
        return self._session.get("/api/v1/events", params={"since": since}).json()

    # -- cluster --
    def list_agents(self) -> List[Dict[str, Any]]:
        return self._session.get("/api/v1/agents").json()

    def list_resource_pools(self) -> List[Dict[str, Any]]:
        """Declared pools (agent/kubernetes/slurm backends, ``rm.hpp``)
        plus implicit agent pools with slot totals (reference
        ``GetResourcePools``)."""
        return self._session.get("/api/v1/resource-pools").json()

    def master_info(self) -> Dict[str, Any]:
        return self._session.get("/api/v1/master").json()

    def whoami(self) -> Dict[str, Any]:
        return self._session.get("/api/v1/auth/whoami").json()

    def create_user(
        self,
        username: str,
        password: str = "",
        admin: bool = False,
        role: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Create a user; ``role`` is admin/user/viewer (RBAC-lite)."""
        body: Dict[str, Any] = {"username": username, "password": password, "admin": admin}
        if role is not None:
            body["role"] = role
        return self._session.post("/api/v1/users", json=body).json()


# -- module-level convenience (reference: client.py module functions bound to
#    a lazily-created default Determined) --

_default_client: Optional[Determined] = None


def login(
    master: Optional[str] = None,
    user: Optional[str] = None,
    password: Optional[str] = None,
) -> Determined:
    """Authenticate (caching the token) and set the default client."""
    global _default_client
    master = master or os.environ.get("DTPU_MASTER") or "http://127.0.0.1:8080"
    if user is not None:
        session = _auth_login(master, user, password or "")
        _default_client = Determined(master, session=session)
    else:
        _default_client = Determined(master)
    return _default_client


def _require_client() -> Determined:
    global _default_client
    if _default_client is None:
        _default_client = Determined()
    return _default_client


def create_experiment(
    config: Union[str, Dict[str, Any]], context_dir: Optional[str] = None
) -> Experiment:
    return _require_client().create_experiment(config, context_dir)


def get_experiment(experiment_id: int) -> Experiment:
    return _require_client().get_experiment(experiment_id)


def get_trial(trial_id: int) -> Trial:
    return _require_client().get_trial(trial_id)


def get_checkpoint(uuid: str) -> Checkpoint:
    return _require_client().get_checkpoint(uuid)


def get_model(name: str) -> Model:
    return _require_client().get_model(name)
