"""Persistent XLA compilation cache wiring.

A supervised restart (``exec/run_trial.py`` TrialSupervisor) builds a fresh
Trainer, whose jitted step closures are new Python objects — jax's
in-process jit cache misses and the attempt pays a full XLA compile.  With
a persistent cache directory configured, the recompile is a disk read
instead (the compiled executable is keyed on the HLO, which is identical
across attempts), which on a large LM is minutes saved per restart.

The directory comes from ``optimizations.compilation_cache_dir`` (the
experiment's declaration, authoritative) or the ``DTPU_COMPILATION_CACHE``
env var (operator-level fallback).  Setup is idempotent per process.

In-process, the cross-trial jit-reuse cache (``train/_jit_cache.py``) sits
a tier above this one: a fresh Trainer in the SAME process (in-process
restart, concurrent/sequential search trials) shares the jitted callable
itself — no retrace, no disk read.  This persistent cache covers the
cross-process half (new attempt process, relaunch after a crash).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger("determined_tpu.utils.compilation_cache")

# path already applied this process (repeat init() calls must not re-log)
_configured: Optional[str] = None


def resolve_cache_dir(config_dir: Optional[str] = None) -> Optional[str]:
    return config_dir or os.environ.get("DTPU_COMPILATION_CACHE") or None


def setup_compilation_cache(config_dir: Optional[str] = None) -> Optional[str]:
    """Point jax's persistent compilation cache at the configured directory.

    Returns the active cache path (None when unconfigured).  Logs one
    warm/cold line so operators can tell from the task log whether a
    restart will hit the cache.
    """
    global _configured
    path = resolve_cache_dir(config_dir)
    if not path:
        return _configured
    path = os.path.abspath(path)
    if _configured == path:
        return path

    import jax

    os.makedirs(path, exist_ok=True)
    entries = sum(1 for e in os.scandir(path) if e.is_file())
    jax.config.update("jax_compilation_cache_dir", path)
    min_secs = os.environ.get("DTPU_COMPILATION_CACHE_MIN_COMPILE_SECS")
    if min_secs is not None:
        # jax's default threshold (1s) is kept unless explicitly overridden:
        # every real TPU step-graph compile clears it, and caching the
        # sub-second CPU executables below it exercises a deserialization
        # path that corrupts the heap on this jax build (observed
        # "corrupted double-linked list" aborts when a warm cache serves a
        # second in-process Trainer on the CPU backend)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", float(min_secs)
        )
    if entries:
        logger.info(
            "compilation cache HIT candidate: %s is warm (%d entries); "
            "restart recompiles load from disk",
            path,
            entries,
        )
    else:
        logger.info(
            "compilation cache MISS: %s is cold (first run); compiles will "
            "populate it",
            path,
        )
    _configured = path
    return path
