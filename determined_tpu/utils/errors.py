"""Exception hierarchy for determined_tpu.

The reference scatters errors across packages (e.g. ``det.errors`` in
harness); we centralise them.
"""


class DeterminedTPUError(Exception):
    """Base class for all determined_tpu errors."""


class InvalidConfigError(DeterminedTPUError):
    """An experiment / cluster config failed validation."""


class CheckpointNotFoundError(DeterminedTPUError):
    """Requested checkpoint does not exist in storage."""


class PreemptedError(DeterminedTPUError):
    """Raised inside a trial when preemption was requested and the
    training loop chose to unwind via exception."""


class ShardMergeConflictError(DeterminedTPUError):
    """Two ranks uploaded conflicting files/metadata for one sharded
    checkpoint (analog of the reference's md5 conflict detection in
    ``core/_checkpoint.py`` merge_resources/merge_metadata)."""


class StoppedError(DeterminedTPUError):
    """The searcher / master requested this trial stop early."""
