"""Exception hierarchy + failure taxonomy for determined_tpu.

The reference scatters errors across packages (e.g. ``det.errors`` in
harness); we centralise them.  The taxonomy drives the supervised-restart
layer (``train/_restart.py``): every trial failure is classified as
PREEMPTED (exit cleanly, the scheduler will re-place the allocation),
TRANSIENT (restart from the latest good checkpoint, counted against
``max_restarts`` — the reference master's restart policy,
``master/internal/trial.go``), or FATAL (no restart will help).
"""

from __future__ import annotations

import enum


class DeterminedTPUError(Exception):
    """Base class for all determined_tpu errors."""


class InvalidConfigError(DeterminedTPUError):
    """An experiment / cluster config failed validation."""


class CheckpointNotFoundError(DeterminedTPUError):
    """Requested checkpoint does not exist in storage."""


class PreemptedError(DeterminedTPUError):
    """Raised inside a trial when preemption was requested and the
    training loop chose to unwind via exception."""


class ShardMergeConflictError(DeterminedTPUError):
    """Two ranks uploaded conflicting files/metadata for one sharded
    checkpoint (analog of the reference's md5 conflict detection in
    ``core/_checkpoint.py`` merge_resources/merge_metadata)."""


class StoppedError(DeterminedTPUError):
    """The searcher / master requested this trial stop early."""


class TransientError(DeterminedTPUError):
    """A failure that a restart from checkpoint is expected to cure
    (network partition, lost gang peer, storage hiccup, injected crash)."""


class FatalTrialError(DeterminedTPUError):
    """A failure no restart will cure (bad config, deterministic user-code
    bug, exhausted restart budget)."""


class RestartBudgetExhaustedError(FatalTrialError):
    """``max_restarts`` transient failures in a row: the supervisor gives
    up and the trial goes terminal (reference: restarts column on the
    trial record; the master stops re-launching past the budget)."""


class PeerLostError(TransientError):
    """A control-plane gang peer stopped responding inside the collective
    deadline.  Raised by ``core/_distributed.py`` instead of hanging the
    gang; classified transient — a supervised restart re-forms the gang."""


class CheckpointCorruptError(DeterminedTPUError):
    """A checkpoint failed manifest verification (missing manifest,
    truncated or bit-flipped file).  Deterministic, so FATAL for retry
    purposes — the resume path falls back to an older checkpoint instead
    (``Trainer._restore_checkpoint``)."""


class FailureKind(enum.Enum):
    """Supervisor-facing classification of a trial failure."""

    PREEMPTED = "preempted"
    TRANSIENT = "transient"
    FATAL = "fatal"


# Deterministic Python "bug" exceptions: re-running the same user code on
# the same checkpoint hits them again, so restarting only burns budget.
_FATAL_BUILTINS = (
    TypeError,
    AttributeError,
    NameError,
    ImportError,
    SyntaxError,
    ZeroDivisionError,
    AssertionError,
    NotImplementedError,
)


def classify_failure(exc: BaseException) -> FailureKind:
    """Map an exception from a trial attempt onto the restart taxonomy.

    Ordering matters: explicit taxonomy classes first, then the
    deterministic-bug builtins, then the reference's default of "any other
    failure is restartable" (``master/internal/trial.go`` restarts every
    non-cancel exit up to max_restarts).
    """
    if isinstance(exc, PreemptedError):
        return FailureKind.PREEMPTED
    if isinstance(exc, TransientError):
        return FailureKind.TRANSIENT
    if isinstance(
        exc,
        (
            FatalTrialError,
            InvalidConfigError,
            CheckpointCorruptError,
            ShardMergeConflictError,
            StoppedError,
        ),
    ):
        return FailureKind.FATAL
    # config parse errors raised as InvalidExperimentConfig (a ValueError
    # subclass defined in config/experiment.py; imported lazily to avoid a
    # utils -> config dependency cycle)
    if type(exc).__name__ == "InvalidExperimentConfig":
        return FailureKind.FATAL
    if isinstance(exc, _FATAL_BUILTINS):
        return FailureKind.FATAL
    return FailureKind.TRANSIENT
