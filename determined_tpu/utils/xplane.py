"""xplane (profiler trace) analysis: per-op device-time tables.

One parser serves three consumers: ``scripts/profile_step.py`` (roofline
accounting), ``scripts/weak_scaling.py`` (collective-vs-compute
attribution of the virtual-mesh scaling curve), and the tensorboard
viewer task (``exec/tensorboard.py`` renders op tables per trial — the
reference wires torch.profiler traces into TensorBoard's plugin,
``_pytorch_context.py:426-462``; here the platform parses its own traces).

Parsing rides the ``xprof`` package's hlo_stats tool (baked into this
image next to jax.profiler); there is no proto-schema copy in-repo.
"""

from __future__ import annotations

import glob
import json
import os
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

# HLO categories that are cross-device communication
COLLECTIVE_CATEGORIES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective",
)


def xplane_files(trace_dir: str) -> List[str]:
    return sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True)
    )


def hlo_op_table(trace_source) -> List[Dict[str, object]]:
    """[{name, category, expression, time_us}] from a trace dir or file list.

    Raises RuntimeError when the xprof tooling is unavailable or the trace
    holds no xplane files.
    """
    try:
        from xprof.convert import raw_to_tool_data
    except Exception as e:  # pragma: no cover - environment-dependent
        raise RuntimeError(f"xprof tooling unavailable: {e}") from e

    files = (
        trace_source
        if isinstance(trace_source, (list, tuple))
        else xplane_files(trace_source)
    )
    if not files:
        raise RuntimeError(f"no .xplane.pb under {trace_source}")
    data, _ = raw_to_tool_data.xspace_to_tool_data(list(files), "hlo_stats", {})
    if isinstance(data, bytes):
        data = data.decode()
    table = json.loads(data)
    if isinstance(table, dict):  # gviz DataTable
        cols = [c.get("label") or c.get("id") or "" for c in table["cols"]]
        rows = [[(c or {}).get("v") for c in r["c"]] for r in table["rows"]]
    else:
        cols = [c["label"] if isinstance(c, dict) else c for c in table[0]]
        rows = table[1:]
    low = [str(c).lower() for c in cols]
    name_i = next(i for i, c in enumerate(low) if "hlo op name" in c or c == "name")
    expr_i = next((i for i, c in enumerate(low) if "expression" in c), name_i)
    time_i = next(i for i, c in enumerate(low) if "total time" in c and "us" in c)
    cat_i = next((i for i, c in enumerate(low) if "category" in c), None)
    merged: Dict[Tuple[str, str, str], float] = defaultdict(float)
    for row in rows:
        key = (
            str(row[name_i]),
            str(row[cat_i]) if cat_i is not None else "",
            str(row[expr_i])[:160],
        )
        merged[key] += float(row[time_i] or 0)
    if merged:
        return [
            {"name": n, "category": c, "expression": e, "time_us": us}
            for (n, c, e), us in sorted(merged.items(), key=lambda kv: -kv[1])
        ]
    # CPU traces carry no per-HLO device rows (hlo_stats is empty); fall
    # back to aggregating the host plane's TraceMe events so the viewer
    # still renders something meaningful off-TPU.  Nested events mean
    # parents include children — a host-activity table, not a roofline.
    return _host_trace_table(files)


def _host_trace_table(files: List[str]) -> List[Dict[str, object]]:
    from tensorflow.tsl.profiler.protobuf import xplane_pb2  # type: ignore

    merged: Dict[str, float] = defaultdict(float)
    for f in files:
        xs = xplane_pb2.XSpace()
        with open(f, "rb") as fh:
            xs.ParseFromString(fh.read())
        for plane in xs.planes:
            if not plane.name.endswith(":CPU"):
                continue
            ev_meta = {m.id: m.name for m in plane.event_metadata.values()}
            for line in plane.lines:
                for ev in line.events:
                    name = ev_meta.get(ev.metadata_id, "?")
                    merged[name] += ev.duration_ps / 1e6  # ps -> us
    return [
        {"name": n, "category": "host", "expression": n, "time_us": us}
        for n, us in sorted(merged.items(), key=lambda kv: -kv[1])
    ]


def split_collectives(ops: List[Dict[str, object]]) -> Tuple[float, float]:
    """(collective_us, other_us) for an op table."""
    coll = other = 0.0
    for op in ops:
        hay = (str(op["category"]) + " " + str(op["name"])).lower()
        if any(c in hay for c in COLLECTIVE_CATEGORIES):
            coll += float(op["time_us"])
        else:
            other += float(op["time_us"])
    return coll, other


def category_totals(ops: List[Dict[str, object]]) -> Dict[str, float]:
    out: Dict[str, float] = defaultdict(float)
    for op in ops:
        out[str(op["category"]) or str(op["name"]).split(".")[0]] += float(
            op["time_us"]
        )
    return dict(sorted(out.items(), key=lambda kv: -kv[1]))
