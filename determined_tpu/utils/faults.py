"""Fault-injection hook points.

Production code calls ``fire(site, **info)`` at the places where real
infrastructure fails: the training step, storage puts/gets, master API
requests, control-plane collectives.  With no injector installed (the
default, always in production) a fire is one ``is None`` check — safe in
hot paths.  Tests install an injector (``tests/faults.py FaultInjector``)
that can raise at a site to simulate a crash, drop a peer, or fail a
storage put; the exception then propagates exactly like the real fault
would, exercising the supervised-restart / manifest-fallback machinery
end to end.

Sites currently wired (a site is just a dotted string; injectors may
glob-match):

- ``train.step``          before each optimizer step (``step=``)
- ``data.prefetch.fetch`` on the prefetch WORKER thread, before each host
                          batch fetch (``batches=`` produced so far); a raise
                          kills the worker and surfaces at the consumer's
                          next ``__next__`` with the original exception type
- ``storage.upload``      before a StorageManager upload (``manager=, src=, storage_id=, paths=``)
- ``storage.upload.done`` after a successful upload (same info)
- ``storage.download``    before a StorageManager download (``manager=, storage_id=, dst=``)
- ``api.request``         before each master HTTP request (``method=, path=``)
- ``serve.generate``      in the serving replica's /v1/generate handler,
                          before admission; a raise answers 500 and bumps
                          the ``http_5xx`` heartbeat stat — the canary
                          bake's regression vehicle
- ``distributed.gather`` / ``distributed.allgather`` / ``distributed.broadcast``
                          before each control-plane collective (``rank=``)
- ``experiment.journal.append``
                          before each experiment-journal record lands
                          (``type=, seq=``); a raise here kills the
                          EXPERIMENT DRIVER at the worst moment — the
                          event happened but the WAL never saw it —
                          exercising journal replay + searcher restore
"""

from __future__ import annotations

import threading
from typing import Any, Optional, Protocol


class Injector(Protocol):
    def fire(self, site: str, **info: Any) -> None: ...


_injector: Optional[Injector] = None
_lock = threading.Lock()


def set_fault_injector(injector: Optional[Injector]) -> None:
    """Install (or with None, remove) the process-global injector."""
    global _injector
    with _lock:
        _injector = injector


def get_fault_injector() -> Optional[Injector]:
    return _injector


def fire(site: str, **info: Any) -> None:
    """Hook point: no-op unless an injector is installed.  An injector's
    ``fire`` may raise — the exception propagates to the call site like
    the real fault it simulates."""
    inj = _injector
    if inj is not None:
        inj.fire(site, **info)
