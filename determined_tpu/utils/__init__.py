from determined_tpu.utils.errors import (  # noqa: F401
    DeterminedTPUError,
    InvalidConfigError,
    CheckpointNotFoundError,
    PreemptedError,
)
