"""``dtpu deploy gcp``: generate a GCP TPU-VM cluster deployment.

Reference: ``det deploy gcp`` (``harness/determined/deploy/gcp/``, which
drives Terraform against GCE).  TPU redesign: the deployment unit is the
**TPU VM** (agents run on the TPU hosts themselves — no GPU-instance +
docker sandwich), and instead of embedding a cloud SDK this emits a
self-contained bundle of ``gcloud`` scripts + startup scripts + a pools
config wired for the master's provisioner, which the operator reviews
and runs.  Zero egress from this tool; everything is reviewable text.

    dtpu deploy gcp --project my-proj --zone us-central2-b \
        --accelerator v5litepod-8 --max-agents 4 --out ./deploy-gcp
    cd deploy-gcp && ./up.sh     # creates master VM + TPU agent VMs
"""

from __future__ import annotations

import json
import os
import stat


MASTER_STARTUP = """#!/bin/bash
# master VM startup: runs the dtpu master as a systemd unit
set -e
mkdir -p /opt/dtpu /var/lib/dtpu
# operator: place the dtpu-master binary + pools.json under /opt/dtpu
# (bake them into the image or pull from your artifact store here)
#
# the provisioner launches autoscaled agents with this startup script:
# generated HERE so the master's own address is baked in (the bundle's
# agent-startup.sh keeps a placeholder only up.sh substitutes)
sed "s/{{master_host}}/$(hostname -i)/" /opt/dtpu/agent-startup.tmpl \\
  > /opt/dtpu/agent-startup.sh || true
cat > /etc/systemd/system/dtpu-master.service <<UNIT
[Unit]
Description=determined-tpu master
After=network-online.target
[Service]
ExecStart=/opt/dtpu/dtpu-master --port {port} --state-dir /var/lib/dtpu/state \\
  --checkpoint-dir {checkpoint_dir} --pools /opt/dtpu/pools.json \\
  --advertised-url http://$(hostname -i):{port}
Restart=always
[Install]
WantedBy=multi-user.target
UNIT
systemctl daemon-reload
systemctl enable --now dtpu-master
"""

AGENT_STARTUP = """#!/bin/bash
# TPU-VM startup: runs the dtpu agent; slots auto-detect the chips
set -e
mkdir -p /opt/dtpu
cat > /etc/systemd/system/dtpu-agent.service <<UNIT
[Unit]
Description=determined-tpu agent
After=network-online.target
[Service]
Environment=PYTHONPATH=/opt/dtpu
ExecStart=/opt/dtpu/dtpu-agent --master-host {master_host} \\
  --master-port {port} --id %H --pool {pool}
Restart=always
[Install]
WantedBy=multi-user.target
UNIT
systemctl daemon-reload
systemctl enable --now dtpu-agent
"""

UP_SH = """#!/bin/bash
# create the master VM, then {agents} TPU agent VM(s)
set -euo pipefail
gcloud compute instances create {name}-master \\
  --project {project} --zone {zone} \\
  --machine-type {master_machine_type} \\
  --metadata-from-file startup-script=master-startup.sh
MASTER_IP=$(gcloud compute instances describe {name}-master \\
  --project {project} --zone {zone} \\
  --format='get(networkInterfaces[0].networkIP)')
if [ {agents} -gt 0 ]; then
  sed "s/{{master_host}}/$MASTER_IP/" agent-startup.tmpl > /tmp/agent-startup.sh
  for i in $(seq 0 {last_agent}); do
    gcloud compute tpus tpu-vm create {name}-agent-$i \\
      --project {project} --zone {zone} \\
      --accelerator-type {accelerator} --version {runtime_version} \\
      --metadata-from-file startup-script=/tmp/agent-startup.sh
  done
fi
echo "master: http://$MASTER_IP:{port}"
"""

DOWN_SH = """#!/bin/bash
set -uo pipefail
if [ {agents} -gt 0 ]; then
  for i in $(seq 0 {last_agent}); do
    gcloud compute tpus tpu-vm delete {name}-agent-$i \\
      --project {project} --zone {zone} --quiet
  done
fi
gcloud compute instances delete {name}-master \\
  --project {project} --zone {zone} --quiet
"""

# provisioner commands the master VM uses to autoscale TPU agent VMs
LAUNCH_CMD = (
    "gcloud compute tpus tpu-vm create {name}-auto-$RANDOM"
    " --project {project} --zone {zone}"
    " --accelerator-type {accelerator} --version {runtime_version}"
    " --metadata-from-file startup-script=/opt/dtpu/agent-startup.sh"
)
TERMINATE_CMD = (
    "gcloud compute tpus tpu-vm delete \"$DTPU_AGENT_ID\""
    " --project {project} --zone {zone} --quiet"
)


def deploy_gcp(args) -> int:
    out = os.path.abspath(args.out)
    os.makedirs(out, exist_ok=True)
    subs = {
        "name": args.name,
        "project": args.project,
        "zone": args.zone,
        "accelerator": args.accelerator,
        "runtime_version": args.runtime_version,
        "agents": args.agents,
        "last_agent": max(args.agents - 1, 0),
        "port": args.port,
        "pool": "default",
        "master_machine_type": args.master_machine_type,
        "checkpoint_dir": args.checkpoint_dir,
        "master_host": "{master_host}",  # substituted by up.sh at create time
    }
    pools = [
        {
            "name": "default",
            "type": "agent",
            "provisioner": {
                "launch_cmd": LAUNCH_CMD.format(**subs),
                "terminate_cmd": TERMINATE_CMD.format(**subs),
                "min_agents": 0,
                "max_agents": args.max_agents,
                "idle_grace_sec": args.idle_grace_sec,
            },
        }
        if args.max_agents > args.agents
        else {"name": "default", "type": "agent"}
    ]
    files = {
        "master-startup.sh": MASTER_STARTUP.format(**subs),
        "agent-startup.tmpl": AGENT_STARTUP.format(**subs),
        "up.sh": UP_SH.format(**subs),
        "down.sh": DOWN_SH.format(**subs),
        "pools.json": json.dumps(pools, indent=2) + "\n",
    }
    for fname, content in files.items():
        path = os.path.join(out, fname)
        with open(path, "w") as f:
            f.write(content)
        if fname.endswith(".sh"):
            os.chmod(path, os.stat(path).st_mode | stat.S_IXUSR | stat.S_IXGRP)
    print(f"wrote {len(files)} files to {out}")
    print(f"review them, then: cd {out} && ./up.sh")
    return 0


def register(deploy_sub) -> None:
    gcp = deploy_sub.add_parser("gcp")
    gcp.add_argument("--project", required=True)
    gcp.add_argument("--zone", required=True)
    gcp.add_argument("--name", default="dtpu")
    gcp.add_argument("--accelerator", default="v5litepod-8")
    gcp.add_argument("--runtime-version", default="v2-alpha-tpuv5-lite")
    gcp.add_argument("--agents", type=int, default=1,
                     help="TPU agent VMs created by up.sh")
    gcp.add_argument("--max-agents", type=int, default=1,
                     help="> --agents enables the provisioner (autoscale)")
    gcp.add_argument("--port", type=int, default=8080)
    gcp.add_argument("--master-machine-type", default="n2-standard-8")
    gcp.add_argument("--checkpoint-dir", default="/var/lib/dtpu/checkpoints",
                     help="shared checkpoint path (GCS fuse mount or NFS)")
    gcp.add_argument("--idle-grace-sec", type=int, default=600)
    gcp.add_argument("--out", default="./deploy-gcp")
    gcp.set_defaults(fn=deploy_gcp)
