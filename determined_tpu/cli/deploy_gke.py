"""``dtpu deploy gke``: generate a GKE deployment (kubernetes pool).

Reference: ``det deploy gke`` (``harness/determined/deploy/gke/``, which
drives gcloud+kubectl against a GKE cluster).  TPU redesign: the master
runs IN the cluster as a Deployment and schedules trials onto the
cluster's TPU node pools through its kubernetes resource-pool backend
(``native/master/rm.hpp``) — pods request ``google.com/tpu`` and GKE
places them.  Apiserver access rides a ``kubectl proxy`` sidecar
(plaintext on localhost, auth handled by the pod's serviceaccount), so
no token ever lands in a config file.  Zero egress from this tool;
everything is reviewable text the operator applies with kubectl.

    dtpu deploy gke --image gcr.io/my-proj/determined-tpu:latest \
        --namespace dtpu --out ./deploy-gke
    cd deploy-gke && ./up.sh
"""

from __future__ import annotations

import json
import os
import stat

NAMESPACE_YAML = """apiVersion: v1
kind: Namespace
metadata:
  name: {namespace}
"""

# the master's serviceaccount may manage Jobs/Pods in its own namespace
# (the watch-based informer also needs watch on jobs)
RBAC_YAML = """apiVersion: v1
kind: ServiceAccount
metadata:
  name: dtpu-master
  namespace: {namespace}
---
apiVersion: rbac.authorization.k8s.io/v1
kind: Role
metadata:
  name: dtpu-master
  namespace: {namespace}
rules:
- apiGroups: ["batch"]
  resources: ["jobs"]
  verbs: ["create", "get", "list", "watch", "delete"]
- apiGroups: [""]
  resources: ["pods", "pods/log"]
  verbs: ["get", "list", "watch"]
---
apiVersion: rbac.authorization.k8s.io/v1
kind: RoleBinding
metadata:
  name: dtpu-master
  namespace: {namespace}
roleRef:
  apiGroup: rbac.authorization.k8s.io
  kind: Role
  name: dtpu-master
subjects:
- kind: ServiceAccount
  name: dtpu-master
  namespace: {namespace}
"""

MASTER_YAML = """apiVersion: v1
kind: PersistentVolumeClaim
metadata:
  name: dtpu-state
  namespace: {namespace}
spec:
  accessModes: ["ReadWriteOnce"]
  resources:
    requests:
      storage: {state_storage}
---
apiVersion: apps/v1
kind: Deployment
metadata:
  name: dtpu-master
  namespace: {namespace}
spec:
  replicas: 1
  strategy:
    type: Recreate   # the journal dir is RWO; never two masters on it
  selector:
    matchLabels: {{app: dtpu-master}}
  template:
    metadata:
      labels: {{app: dtpu-master}}
    spec:
      serviceAccountName: dtpu-master
      containers:
      - name: master
        image: {image}
        command: ["/opt/dtpu/dtpu-master",
                  "--port", "{port}",
                  "--state-dir", "/var/lib/dtpu/state",
                  "--checkpoint-dir", "{checkpoint_dir}",
                  "--pools", "/etc/dtpu/pools.json",
                  "--advertised-url",
                  "http://dtpu-master.{namespace}.svc:{port}"]
        ports:
        - containerPort: {port}
        volumeMounts:
        - {{name: state, mountPath: /var/lib/dtpu}}
        - {{name: pools, mountPath: /etc/dtpu}}
      # apiserver access without tokens-in-files: the sidecar proxies
      # localhost:8001 -> apiserver using the pod's serviceaccount
      - name: kubectl-proxy
        image: {kubectl_image}
        command: ["kubectl", "proxy", "--port=8001"]
      volumes:
      - name: state
        persistentVolumeClaim: {{claimName: dtpu-state}}
      - name: pools
        configMap: {{name: dtpu-pools}}
---
apiVersion: v1
kind: Service
metadata:
  name: dtpu-master
  namespace: {namespace}
spec:
  type: {service_type}
  selector: {{app: dtpu-master}}
  ports:
  - port: {port}
    targetPort: {port}
---
# headless service for trial pods: gives rank-0 pods stable DNS the
# other ranks dial for jax.distributed rendezvous (coordinator_pattern)
apiVersion: v1
kind: Service
metadata:
  name: trainers
  namespace: {namespace}
spec:
  clusterIP: None
  selector: {{app: dtpu-trial}}
"""

UP_SH = """#!/bin/bash
set -euo pipefail
kubectl apply -f manifests/namespace.yaml
kubectl apply -f manifests/rbac.yaml
kubectl -n {namespace} create configmap dtpu-pools \\
  --from-file=pools.json --dry-run=client -o yaml | kubectl apply -f -
kubectl apply -f manifests/master.yaml
kubectl -n {namespace} rollout status deploy/dtpu-master
echo "master service:"
kubectl -n {namespace} get svc dtpu-master
"""

DOWN_SH = """#!/bin/bash
set -uo pipefail
kubectl delete namespace {namespace}
"""


def deploy_gke(args) -> int:
    out = os.path.abspath(args.out)
    os.makedirs(os.path.join(out, "manifests"), exist_ok=True)
    subs = {
        "namespace": args.namespace,
        "image": args.image,
        "kubectl_image": args.kubectl_image,
        "port": args.port,
        "checkpoint_dir": args.checkpoint_dir,
        "state_storage": args.state_storage,
        "service_type": args.service_type,
    }
    pools = [
        {
            "name": "default",
            "type": "kubernetes",
            "kubernetes": {
                # the kubectl-proxy sidecar: no token in this file
                "apiserver": "http://127.0.0.1:8001",
                "namespace": args.namespace,
                "image": args.image,
                "slots_per_node": args.slots_per_node,
                "coordinator_pattern": "{job}.trainers.{namespace}.svc",
                **({"quota_slots": args.quota_slots} if args.quota_slots else {}),
            },
        }
    ]
    files = {
        "manifests/namespace.yaml": NAMESPACE_YAML.format(**subs),
        "manifests/rbac.yaml": RBAC_YAML.format(**subs),
        "manifests/master.yaml": MASTER_YAML.format(**subs),
        "pools.json": json.dumps(pools, indent=2) + "\n",
        "up.sh": UP_SH.format(**subs),
        "down.sh": DOWN_SH.format(**subs),
    }
    for fname, content in files.items():
        path = os.path.join(out, fname)
        with open(path, "w") as f:
            f.write(content)
        if fname.endswith(".sh"):
            os.chmod(path, os.stat(path).st_mode | stat.S_IXUSR | stat.S_IXGRP)
    print(f"wrote {len(files)} files to {out}")
    print(f"review them, then: cd {out} && ./up.sh")
    return 0


def register(deploy_sub) -> None:
    gke = deploy_sub.add_parser("gke")
    gke.add_argument("--image", required=True,
                     help="determined-tpu image (master+agent binaries + harness)")
    gke.add_argument("--namespace", default="dtpu")
    gke.add_argument("--port", type=int, default=8080)
    gke.add_argument("--slots-per-node", type=int, default=4,
                     help="TPU chips per GKE node (google.com/tpu per pod)")
    gke.add_argument("--quota-slots", type=int, default=0,
                     help="per-namespace in-flight slot quota (0 = unlimited)")
    gke.add_argument("--checkpoint-dir", default="/var/lib/dtpu/checkpoints",
                     help="shared checkpoint path (GCS fuse / Filestore mount)")
    gke.add_argument("--state-storage", default="10Gi")
    gke.add_argument("--service-type", default="ClusterIP",
                     choices=["ClusterIP", "LoadBalancer"])
    gke.add_argument("--kubectl-image", default="bitnami/kubectl:latest")
    gke.add_argument("--out", default="./deploy-gke")
    gke.set_defaults(fn=deploy_gke)
