"""Local cluster deployment: ``dtpu deploy local up|down|status``.

Reference: ``det deploy local`` (``harness/determined/deploy/local/``), which
brings up master+db+agents with docker-compose.  TPU redesign: there is no
container sandwich — TPU VMs run training directly on the host — so a local
cluster is plain process supervision: spawn ``dtpu-master`` and N
``dtpu-agent`` processes detached, record their pids under the cluster
directory, and tear down by pid.  The same binaries a production site runs
under systemd are what ``deploy local`` runs under your shell.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Optional


def _default_cluster_dir() -> str:
    return os.path.join(os.path.expanduser("~"), ".dtpu", "cluster")


def _find_binary(name: str, env_var: str) -> Optional[str]:
    """Locate a native binary: env override, then the in-repo build dir,
    then PATH."""
    override = os.environ.get(env_var)
    if override and os.path.exists(override):
        return override
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    candidate = os.path.join(repo, "native", "build", name)
    if os.path.exists(candidate):
        return candidate
    import shutil

    return shutil.which(name)


def _cluster_file(cluster_dir: str) -> str:
    return os.path.join(cluster_dir, "cluster.json")


def _load_cluster(cluster_dir: str) -> Optional[dict]:
    try:
        with open(_cluster_file(cluster_dir)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def deploy_local_up(args) -> int:
    cluster_dir = os.path.abspath(args.cluster_dir)
    existing = _load_cluster(cluster_dir)
    if existing and _alive(existing.get("master_pid", -1)):
        print(f"cluster already running (master pid {existing['master_pid']}, "
              f"{existing['url']}); `dtpu deploy local down` first")
        return 1
    if existing:
        # half-dead cluster (master crashed, agents survive retrying the
        # old port): stop the stragglers before the record is overwritten,
        # or nothing could ever reach them again.  Wait for them to die —
        # a replacement agent reuses the same state dir and slots.
        stale = [p for p in existing.get("agent_pids", []) if _alive(p)]
        for pid in stale:
            print(f"stopping stale agent pid {pid} from previous cluster")
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass
        deadline = time.time() + 5
        while time.time() < deadline and any(_alive(p) for p in stale):
            time.sleep(0.2)
        for pid in stale:
            if _alive(pid):
                try:
                    os.kill(pid, signal.SIGKILL)
                except OSError:
                    pass
    master_bin = _find_binary("dtpu-master", "DTPU_MASTER_BIN")
    agent_bin = _find_binary("dtpu-agent", "DTPU_AGENT_BIN")
    if not master_bin or not agent_bin:
        print("dtpu-master / dtpu-agent binaries not found "
              "(build native/ or set DTPU_MASTER_BIN / DTPU_AGENT_BIN)")
        return 1
    os.makedirs(cluster_dir, exist_ok=True)
    port = args.port or _free_port()
    url = f"http://127.0.0.1:{port}"
    log_dir = os.path.join(cluster_dir, "logs")
    os.makedirs(log_dir, exist_ok=True)

    master_cmd = [
        master_bin,
        "--host", "127.0.0.1",
        "--port", str(port),
        "--state-dir", os.path.join(cluster_dir, "state"),
        "--checkpoint-dir", os.path.join(cluster_dir, "checkpoints"),
        "--scheduler", args.scheduler,
    ]
    if args.pools:
        master_cmd += ["--pools", os.path.abspath(args.pools)]
    with open(os.path.join(log_dir, "master.log"), "ab") as log:
        master = subprocess.Popen(
            master_cmd, stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True,
        )

    deadline = time.time() + 15
    up = False
    while time.time() < deadline:
        try:
            import urllib.request

            urllib.request.urlopen(url + "/api/v1/master", timeout=1).read()
            up = True
            break
        except Exception:  # noqa: BLE001 - still booting
            if master.poll() is not None:
                break
            time.sleep(0.2)
    if not up:
        print(f"master did not come up; see {log_dir}/master.log")
        if master.poll() is None:
            master.terminate()
        return 1

    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    agent_pids = []
    for i in range(args.agents):
        agent_cmd = [
            agent_bin,
            "--master-host", "127.0.0.1",
            "--master-port", str(port),
            "--id", f"local-agent-{i}",
            "--state-dir", os.path.join(cluster_dir, f"agent-{i}"),
        ]
        if args.slots:
            agent_cmd += ["--slots", str(args.slots)]
        with open(os.path.join(log_dir, f"agent-{i}.log"), "ab") as log:
            agent = subprocess.Popen(
                agent_cmd, env=env, stdout=log, stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        agent_pids.append(agent.pid)

    with open(_cluster_file(cluster_dir), "w") as f:
        json.dump(
            {"url": url, "port": port, "master_pid": master.pid,
             "agent_pids": agent_pids},
            f,
        )
    print(f"cluster up: {url} (master pid {master.pid}, "
          f"{len(agent_pids)} agent(s))")
    print(f"export DTPU_MASTER={url}")
    return 0


def deploy_local_down(args) -> int:
    cluster_dir = os.path.abspath(args.cluster_dir)
    cluster = _load_cluster(cluster_dir)
    if not cluster:
        print(f"no cluster recorded under {cluster_dir}")
        return 1
    pids = [cluster.get("master_pid")] + list(cluster.get("agent_pids", []))
    pids = [p for p in pids if p and _alive(p)]
    for pid in pids:
        try:
            os.kill(pid, signal.SIGTERM)
        except OSError:
            pass
    deadline = time.time() + 10
    while time.time() < deadline and any(_alive(p) for p in pids):
        time.sleep(0.2)
    for pid in pids:
        if _alive(pid):
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
    os.remove(_cluster_file(cluster_dir))
    print(f"cluster down ({len(pids)} process(es) stopped)")
    return 0


def deploy_local_status(args) -> int:
    cluster_dir = os.path.abspath(args.cluster_dir)
    cluster = _load_cluster(cluster_dir)
    if not cluster:
        print(f"no cluster recorded under {cluster_dir}")
        return 1
    master_ok = _alive(cluster.get("master_pid", -1))
    agents_ok = sum(1 for p in cluster.get("agent_pids", []) if _alive(p))
    print(f"master: {'up' if master_ok else 'DOWN'} "
          f"(pid {cluster.get('master_pid')}, {cluster.get('url')})")
    print(f"agents: {agents_ok}/{len(cluster.get('agent_pids', []))} up")
    return 0 if master_ok else 1


def register(sub) -> None:
    deploy = sub.add_parser("deploy").add_subparsers(dest="verb", required=True)

    from determined_tpu.cli import deploy_gcp, deploy_gke

    deploy_gcp.register(deploy)
    deploy_gke.register(deploy)
    local = deploy.add_parser("local").add_subparsers(dest="action", required=True)
    up = local.add_parser("up")
    up.add_argument("--agents", type=int, default=1)
    up.add_argument("--slots", type=int, default=0, help="0 = agent auto-detect")
    up.add_argument("--port", type=int, default=0, help="0 = pick a free port")
    up.add_argument("--scheduler", default="priority",
                    choices=["priority", "fair_share"])
    up.add_argument("--pools", default=None, help="pools.json for RM backends")
    up.add_argument("--cluster-dir", default=_default_cluster_dir())
    up.set_defaults(fn=deploy_local_up)
    down = local.add_parser("down")
    down.add_argument("--cluster-dir", default=_default_cluster_dir())
    down.set_defaults(fn=deploy_local_down)
    status = local.add_parser("status")
    status.add_argument("--cluster-dir", default=_default_cluster_dir())
    status.set_defaults(fn=deploy_local_status)


if __name__ == "__main__":
    sys.exit(0)
