import sys

from determined_tpu.cli.main import main

sys.exit(main())
