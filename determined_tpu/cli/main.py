"""dtpu CLI: the ``det`` command-line equivalent.

Reference: ``harness/determined/cli/`` (declarative argparse per noun:
experiment/trial/agent/checkpoint/master/user).  Built on the Python SDK
(``determined_tpu.client``) the way the reference CLI sits on
``experimental/client.py``; authentication follows the reference contract
(token cache in ``~/.dtpu/auth.json``, auto-login as the ``determined``
user when no credentials are given; ``common/api/authentication.py``).
``run-local`` drives the in-process LocalExperiment runner for masterless
single-host searches.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional


def _client(args):
    from determined_tpu.client import Determined

    url = args.master or os.environ.get("DTPU_MASTER", "http://127.0.0.1:8080")
    # --cert rides the env so every Session (SDK, bindings, core) picks it
    # up without threading it through each constructor
    if getattr(args, "cert", None):
        os.environ["DTPU_MASTER_CERT"] = args.cert
    return Determined(url, user=getattr(args, "user", None) or None)


def _print_json(obj: Any) -> None:
    print(json.dumps(obj, indent=2, sort_keys=True, default=str))


def _table(rows: List[Dict[str, Any]], cols: List[str]) -> None:
    if not rows:
        print("(none)")
        return
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows)) for c in cols}
    print("  ".join(c.upper().ljust(widths[c]) for c in cols))
    for r in rows:
        print("  ".join(str(r.get(c, "")).ljust(widths[c]) for c in cols))


# ---- auth ------------------------------------------------------------------


def do_login(args) -> int:
    from determined_tpu import client

    url = args.master or os.environ.get("DTPU_MASTER", "http://127.0.0.1:8080")
    username = args.user or "determined"
    password = args.password
    if password is None:
        if sys.stdin.isatty():
            import getpass

            password = getpass.getpass(f"password for {username}: ")
        else:
            password = ""
    d = client.login(url, user=username, password=password)
    who = d.whoami()
    print(f"logged in as {who['username']} (admin={who['admin']}) at {url}")
    return 0


def do_whoami(args) -> int:
    _print_json(_client(args).whoami())
    return 0


def user_create(args) -> int:
    if args.admin and args.role in ("user", "viewer"):
        print(f"error: --admin contradicts --role {args.role}", file=sys.stderr)
        return 1
    _client(args).create_user(
        args.username, args.password or "", args.admin, role=args.role
    )
    print(f"created user {args.username}")
    return 0


def user_list(args) -> int:
    rows = _client(args).session.get("/api/v1/users").json()
    _table(rows, ["username", "role", "admin"])
    return 0


# ---- experiment ------------------------------------------------------------


def exp_create(args) -> int:
    d = _client(args)
    context_bytes = None
    if getattr(args, "context_dir", None):
        from determined_tpu.common import build_context

        context_bytes = build_context(args.context_dir)
        print(f"context: {args.context_dir} ({len(context_bytes)} bytes packed)")
    exp = d.create_experiment(
        args.config,
        context_dir=args.context_dir,
        context_bytes=context_bytes,
        template=getattr(args, "template", None),
    )
    print(f"Created experiment {exp.id}")
    if args.follow:
        return exp_wait(args, exp.id)
    return 0


def exp_wait(args, exp_id: int) -> int:
    exp = _client(args).get_experiment(exp_id)
    last_state = None
    while True:
        exp.reload()
        if exp.state != last_state:
            print(f"state: {exp.state} (progress {exp.progress:.0%})")
            last_state = exp.state
        if exp.state in ("COMPLETED", "CANCELED", "ERROR"):
            return 0 if exp.state == "COMPLETED" else 1
        time.sleep(2)


def exp_list(args) -> int:
    _table(
        [
            {
                "id": e.id,
                "name": e.get("name", ""),
                "workspace": e.get("workspace", ""),
                "state": e.state,
                "progress": f"{e.progress:.0%}",
                "trials": len(e.get("trials", [])),
            }
            for e in _client(args).list_experiments(
                workspace=getattr(args, "workspace", None),
                project=getattr(args, "project", None),
            )
        ],
        ["id", "name", "workspace", "state", "progress", "trials"],
    )
    return 0


def exp_describe(args) -> int:
    _print_json(_client(args).get_experiment(args.id).to_dict())
    return 0


def exp_fork(args) -> int:
    import yaml

    overrides = None
    if args.config_overrides:
        with open(args.config_overrides) as f:
            overrides = yaml.safe_load(f)
        if not isinstance(overrides, dict):
            print(
                f"error: {args.config_overrides} must contain a yaml mapping",
                file=sys.stderr,
            )
            return 1
    exp = _client(args).get_experiment(args.id)
    new = exp.continue_(overrides) if args.verb == "continue" else exp.fork(overrides)
    past = "continued" if args.verb == "continue" else "forked"
    print(f"{past} experiment {args.id} -> {new.id}")
    if args.follow:
        return exp_wait(args, new.id)
    return 0


def exp_delete(args) -> int:
    _client(args).get_experiment(args.id).delete()
    print(f"deleted experiment {args.id}")
    return 0


def exp_signal(args) -> int:
    exp = _client(args).get_experiment(args.id)
    exp = getattr(exp, args.verb)()
    print(f"experiment {args.id}: {exp.state}")
    return 0


# ---- searcher simulation (trial-free; no master required) -------------------


def searcher_simulate(args) -> int:
    """Replay search methods against a seeded learning-curve model and
    print a best-metric-vs-budget table — method choice and bracket/
    population math in milliseconds, no device time (docs/searchers.md)."""
    import yaml

    from determined_tpu import searcher as searcher_mod
    from determined_tpu.config.experiment import (
        ExperimentConfig,
        InvalidExperimentConfig,
    )

    if args.config:
        with open(args.config) as f:
            cfg = ExperimentConfig.parse(yaml.safe_load(f))
    else:
        # built-in lr-search space, matched to the synthetic curve model
        cfg = ExperimentConfig.parse(
            {
                "name": "searcher-simulate",
                "hyperparameters": {
                    "lr": {"type": "log", "minval": -4, "maxval": -1}
                },
                "searcher": {
                    "name": "random",
                    "metric": "validation_loss",
                    "max_trials": 16,
                    "max_time": 64,
                    "num_rungs": 3,
                    "divisor": 4,
                },
            }
        )
    methods = [m.strip() for m in args.methods.split(",") if m.strip()]
    from determined_tpu.experiment import ExperimentJournalError

    try:
        if args.journal:
            path = args.journal
            if os.path.isdir(path):
                from determined_tpu.experiment import journal_path

                path = journal_path(path)
            model = searcher_mod.JournalCurveModel.from_journal(
                path, cfg.searcher.metric, cfg.searcher.time_metric or "batches"
            )
        else:
            model = searcher_mod.SyntheticCurveModel(args.seed)
        reports = searcher_mod.compare_methods(
            cfg, methods, model, seed=args.seed, report_period=args.period
        )
    except (InvalidExperimentConfig, ExperimentJournalError, ValueError) as e:
        # covers unknown methods AND a missing/empty --journal
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        _print_json(
            [
                {
                    "method": r.method,
                    "seed": r.seed,
                    "trials_created": r.trials_created,
                    "total_units": r.total_units,
                    "best_metric": r.best_metric,
                    "best_trial": r.best_trial,
                    "best_hparams": r.best_hparams,
                    "curve": r.curve[-32:],
                    "lineage": {
                        str(k): v for k, v in r.lineage.items() if v is not None
                    },
                }
                for r in reports
            ]
        )
        return 0
    print(searcher_mod.format_comparison(reports))
    return 0


# ---- local experiment recovery (journal-backed; no master required) ---------


def exp_status_local(args) -> int:
    """Digest a LocalExperiment's journal: what completed, what's in
    flight, whether the directory is resumable (docs/fault-tolerance.md,
    "Experiment recovery & preemption")."""
    from determined_tpu.experiment import ExperimentJournalError, experiment_status

    try:
        st = experiment_status(args.checkpoint_dir)
    except ExperimentJournalError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        _print_json(st)
        return 0
    print(f"experiment:  {st['name'] or '(unnamed)'}")
    print(f"status:      {st['status']}" + ("  (resumable)" if st["resumable"] else ""))
    print(f"entrypoint:  {st['entrypoint'] or '(unknown)'}")
    if st.get("cluster"):
        # a resumed operator needs the master this search is attached to
        print(
            f"cluster:     experiment {st['cluster']['experiment_id']} "
            f"at {st['cluster']['master_url']}"
        )
    print(
        f"trials:      {st['trials_completed']} completed, "
        f"{st['trials_in_flight']} in flight, {st['trials_created']} created"
    )
    _table(
        [
            {
                "trial": t["request_id"],
                "state": t["state"],
                "steps": t["steps_completed"] if t["steps_completed"] is not None else "",
                "checkpoint": t["checkpoint"] or "",
            }
            for t in st["trials"]
        ],
        ["trial", "state", "steps", "checkpoint"],
    )
    return 0


def exp_profile_local(args) -> int:
    """Goodput ledger for a LOCAL experiment directory: where every second
    of wall-clock went (docs/observability.md).  Reads the Chrome trace
    events exported under ``<dir>/traces/`` (``observability.trace_export:
    true``); ``--xplane`` additionally summarizes a sampled jax.profiler
    window so the host timeline can be checked against device truth."""
    from determined_tpu.observability import (
        compute_ledger,
        format_ledger_text,
        load_trace_events,
    )

    traces_dir = os.path.join(args.checkpoint_dir, "traces")
    events = load_trace_events(traces_dir)
    if not events:
        print(
            f"error: no trace events under {traces_dir} (run the experiment "
            "with observability.trace_export: true)",
            file=sys.stderr,
        )
        return 2
    ledger = compute_ledger(events)

    # optional device-side cross-check: a jax.profiler xplane window
    # (profiling.trace) parsed into an op table via utils/xplane.py
    xplane_summary = None
    xplane_dir = args.xplane or os.path.join(traces_dir, "xplane")
    if args.xplane and not os.path.isdir(args.xplane):
        # an explicit request that cannot be honored must not be silent
        # (the default-path probe, by contrast, is best-effort)
        print(f"warning: --xplane {args.xplane} is not a directory", file=sys.stderr)
    if os.path.isdir(xplane_dir):
        try:
            from determined_tpu.utils import xplane as xplane_mod

            ops = xplane_mod.hlo_op_table(xplane_dir)
            coll, other = xplane_mod.split_collectives(ops)
            xplane_summary = {
                "top_ops": ops[:10],
                "category_totals": xplane_mod.category_totals(ops),
                "collective_us": coll,
                "compute_us": other,
            }
        except Exception as e:  # noqa: BLE001 - best effort
            xplane_summary = {"error": str(e)}

    if args.json:
        out = {"ledger": ledger}
        if xplane_summary is not None:
            out["xplane"] = xplane_summary
        _print_json(out)
        return 0
    print(format_ledger_text(ledger))
    if xplane_summary and "category_totals" in xplane_summary:
        print("\nxplane device-time categories (us):")
        for cat, us in list(xplane_summary["category_totals"].items())[:8]:
            print(f"  {cat:<24} {us:>12.1f}")
    return 0


def exp_resume_local(args) -> int:
    """Resume a crashed/preempted driver experiment from its journal.

    The journal records the experiment config and trial entrypoint, so the
    directory alone is enough; ``--entrypoint`` overrides (e.g. after a
    module rename).  A journal with a ``cluster_attached`` record resumes
    as a ClusterExperiment — the driver re-attaches to its master
    experiment (``-m`` overrides the journaled master url).  Exits 75
    (EX_TEMPFAIL) if the resumed run is itself preempted — still
    resumable.
    """
    from determined_tpu.config.experiment import ExperimentConfig
    from determined_tpu.experiment import (
        PREEMPTED_EXIT_CODE,
        ClusterExperiment,
        ExperimentJournalError,
        LocalExperiment,
        journal_path,
        read_journal,
    )

    try:
        replay = read_journal(journal_path(args.checkpoint_dir))
    except ExperimentJournalError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if replay.status == "completed":
        print("experiment already completed; nothing to resume")
        return 0
    started = replay.started or {}
    entrypoint = args.entrypoint or started.get("entrypoint")
    if not entrypoint:
        print(
            "error: journal records no trial entrypoint; pass --entrypoint "
            "pkg.module:TrialClass",
            file=sys.stderr,
        )
        return 2
    if not started.get("config"):
        print("error: journal records no experiment config", file=sys.stderr)
        return 2
    cfg = ExperimentConfig.parse(started["config"])
    try:
        if replay.cluster is not None:
            # cluster-driven search: re-attach to the journaled master
            ns = argparse.Namespace(
                master=args.master or replay.cluster.get("master_url"),
                user=getattr(args, "user", None),
                cert=getattr(args, "cert", None),
            )
            exp = ClusterExperiment(
                cfg,
                entrypoint,
                session=_client(ns).session,
                checkpoint_dir=args.checkpoint_dir,
                seed=started.get("seed"),
            )
            summary = exp.resume()
        else:
            module_name, _, class_name = entrypoint.partition(":")
            sys.path.insert(0, os.getcwd())
            trial_cls = getattr(importlib.import_module(module_name), class_name)
            lexp = LocalExperiment(
                cfg,
                trial_cls,
                checkpoint_dir=args.checkpoint_dir,
                seed=started.get("seed"),
            )
            summary = lexp.resume(serial=args.serial)
    except ExperimentJournalError as e:
        # e.g. the original driver is still alive and owns the journal
        print(f"error: {e}", file=sys.stderr)
        return 2
    _print_json(summary)
    return PREEMPTED_EXIT_CODE if summary.get("status") == "preempted" else 0


# ---- trial -----------------------------------------------------------------


def trial_describe(args) -> int:
    _print_json(_client(args).get_trial(args.id).to_dict())
    return 0


def trial_logs(args) -> int:
    for line in _client(args).get_trial(args.id).logs(follow=args.follow):
        print(line)
    return 0


def trial_metrics(args) -> int:
    _print_json(list(_client(args).get_trial(args.id).iter_metrics(group=args.group)))
    return 0


# ---- agents / checkpoints / models / master --------------------------------


def agent_list(args) -> int:
    _table(_client(args).list_agents(), ["id", "host", "slots", "used_slots"])
    return 0


def pool_list(args) -> int:
    _table(
        _client(args).list_resource_pools(),
        ["name", "type", "agents", "slots", "used_slots", "provisioned"],
    )
    return 0


def checkpoint_list(args) -> int:
    _table(
        [
            {
                "uuid": c.uuid,
                "trial_id": c.trial_id,
                "steps": c.metadata.get("steps_completed"),
            }
            for c in _client(args).list_checkpoints()
        ],
        ["uuid", "trial_id", "steps"],
    )
    return 0


def checkpoint_download(args) -> int:
    path = _client(args).get_checkpoint(args.uuid).download(args.output)
    print(path)
    return 0


def model_create(args) -> int:
    m = _client(args).create_model(args.name, description=args.description or "")
    print(f"created model {m.name}")
    return 0


def model_list(args) -> int:
    rows = []
    for m in _client(args).get_models():
        versions = m.get("versions") or []
        latest = max((int(v.get("version") or 0) for v in versions), default=0)
        rows.append(
            {
                "name": m.name,
                "versions": len(versions),
                "latest": f"v{latest}" if latest else "-",
            }
        )
    _table(rows, ["name", "versions", "latest"])
    return 0


def model_show(args) -> int:
    model = _client(args).get_model(args.name).to_dict()
    if args.json:
        _print_json(model)
        return 0
    print(f"model {model['name']}")
    if model.get("labels"):
        print(f"  labels: {', '.join(model['labels'])}")
    for v in model.get("versions") or []:
        lineage = []
        if v.get("source_trial_id"):
            lineage.append(f"trial {v['source_trial_id']}")
        if v.get("source_experiment_id"):
            lineage.append(f"experiment {v['source_experiment_id']}")
        print(
            f"  v{v['version']}: checkpoint {v.get('checkpoint_uuid')}"
            + (f" ({', '.join(lineage)})" if lineage else "")
        )
        if v.get("storage_path"):
            print(f"      path: {v['storage_path']}")
        if v.get("metrics"):
            print(f"      metrics: {json.dumps(v['metrics'], sort_keys=True)}")
    return 0


def model_register(args) -> int:
    from determined_tpu.experiment import registry as registry_mod

    metrics = {}
    for kv in args.metric or []:
        key, _, val = kv.partition("=")
        try:
            metrics[key] = float(val)
        except ValueError:
            metrics[key] = val
    v = registry_mod.register_version(
        _client(args).session,
        args.name,
        checkpoint_uuid=args.checkpoint_uuid,
        storage_path=args.storage_path,
        source_trial_id=args.trial_id,
        source_experiment_id=args.experiment_id,
        metrics=metrics or None,
        labels=args.label or None,
        version=args.version,
    )
    print(f"registered {args.name}@v{v['version']} "
          f"(checkpoint {v['checkpoint_uuid']})")
    return 0


def model_promote(args) -> int:
    from determined_tpu.experiment import registry as registry_mod

    session = _client(args).session
    registry_mod.ensure_model(session, args.name)
    v = session.post(
        f"/api/v1/models/{args.name}/promote", json={"trial_id": args.trial_id}
    ).json()
    print(f"promoted trial {args.trial_id} -> {args.name}@v{v['version']} "
          f"(checkpoint {v['checkpoint_uuid']})")
    return 0


def model_pull(args) -> int:
    """Materialize a registry version's checkpoint locally: copy from its
    shared-storage path when this host can see it, else download through
    the master's checkpoint route."""
    import shutil as _shutil

    from determined_tpu.experiment import registry as registry_mod

    client = _client(args)
    ver = registry_mod.resolve_version(client.session, args.ref)
    target = args.output or f"{ver['model']}-v{ver['version']}"
    src = ver.get("storage_path") or ""
    if os.path.isdir(src):
        if os.path.exists(target):
            print(f"error: {target} already exists", file=sys.stderr)
            return 2
        _shutil.copytree(src, target)
        print(target)
        return 0
    path = client.get_checkpoint(ver["checkpoint_uuid"]).download(target)
    print(path)
    return 0


def model_deploy(args) -> int:
    """Rolling deploy: walk the serving fleet one replica at a time onto
    a registry version (drain -> relaunch -> next; docs/registry.md).
    ``--canary F`` rolls only that cohort first and bakes it against the
    pre-roll error-rate/latency baseline before finishing the roll."""
    import time as _time

    from determined_tpu.experiment import registry as registry_mod

    session = _client(args).session
    name, version = registry_mod.parse_model_ref(args.ref)
    body = {"model": name, "version": version}
    if args.canary is not None:
        body["canary_fraction"] = args.canary
        body["bake_seconds"] = args.bake_seconds
        body["min_requests"] = args.canary_min_requests
        if args.rollback_on_regression:
            body["rollback_on_regression"] = True
    state = session.post("/api/v1/serving/deploy", json=body).json()
    mode = ""
    canary = state.get("canary") or {}
    if canary.get("count"):
        mode = f" (canary cohort: {canary['count']})"
    print(f"deploy {state['id']}: rolling {state['target']} "
          f"over {len(state.get('pending') or [])} replica(s){mode}")
    if not args.wait:
        print(state["status"])
        return 0
    deadline = _time.time() + args.timeout
    phase = state.get("phase")
    while _time.time() < deadline:
        state = session.get("/api/v1/serving/deploy").json()
        if state.get("phase") != phase:
            phase = state.get("phase")
            print(f"deploy {state['id']}: phase {phase}")
        if state["status"] != "rolling":
            break
        _time.sleep(1.0)
    detail = f" ({state['detail']})" if state.get("detail") else ""
    print(f"deploy {state['id']}: {state['status']}{detail}")
    canary = state.get("canary") or {}
    if canary.get("verdict"):
        stat = f" — regressed stat: {canary['offending_stat']}" \
            if canary.get("offending_stat") else ""
        print(f"canary verdict: {canary['verdict']}{stat}")
    return 0 if state["status"] == "completed" else 1


# ---- serving fleet (master-side replica supervisor) -------------------------


def fleet_set(args) -> int:
    """Declare the fleet spec: the master's supervisor launches replicas
    as agent tasks and relaunches any that die (docs/serving.md)."""
    config = {}
    if args.slots is not None:
        config["resources"] = {"slots": args.slots}
    for kv in args.env or []:
        key, _, val = kv.partition("=")
        config.setdefault("env", {})[key] = val
    fleet = _client(args).set_serving_fleet(
        args.ref, args.target, pool=args.pool, config=config or None
    )
    print(f"fleet: {fleet['model']}@v{fleet['version']} "
          f"target {fleet['target']} ({fleet['status']})")
    return 0


def fleet_status(args) -> int:
    from determined_tpu.api.session import NotFoundError

    try:
        fleet = _client(args).get_serving_fleet()
    except NotFoundError:
        print("no fleet spec declared", file=sys.stderr)
        return 1
    if args.json:
        _print_json(fleet)
        return 0
    detail = f" — {fleet['detail']}" if fleet.get("detail") else ""
    print(f"{fleet['model']}@v{fleet['version']} target {fleet['target']} "
          f"status {fleet['status']}{detail}")
    for slot in fleet.get("slots") or []:
        state = "gave-up" if slot.get("gave_up") else (
            "live" if slot.get("replica_id") else "launching")
        err = f" last_error={slot['last_error']!r}" if slot.get("last_error") else ""
        print(f"  slot {slot['index']}: {state} task={slot.get('task_id') or '-'} "
              f"replica={slot.get('replica_id') or '-'} "
              f"launches={slot.get('launches', 0)} "
              f"failures={slot.get('failures', 0)}{err}")
    return 0 if fleet["status"] != "degraded" else 1


def model_register_version(args) -> int:
    v = _client(args).get_model(args.name).register_version(args.checkpoint_uuid)
    print(f"registered {args.name} version {v.version}")
    return 0


def master_info(args) -> int:
    _print_json(_client(args).master_info())
    return 0


# ---- templates --------------------------------------------------------------


def template_set(args) -> int:
    import yaml

    with open(args.config) as f:
        _client(args).set_template(args.name, yaml.safe_load(f))
    print(f"template {args.name} set")
    return 0


def template_list(args) -> int:
    _table(_client(args).list_templates(), ["name"])
    return 0


def template_describe(args) -> int:
    _print_json(_client(args).get_template(args.name))
    return 0


def template_remove(args) -> int:
    _client(args).delete_template(args.name)
    print(f"template {args.name} removed")
    return 0


# ---- tensorboard / tasks ---------------------------------------------------


def tensorboard_start(args) -> int:
    d = _client(args)
    info = d.start_tensorboard(experiment_ids=args.experiment_ids or [])
    info = d.wait_task_ready(info["id"], timeout=args.timeout)
    url = f"{d.master}{info['proxy_url']}?dtpu_token={d.session.token}"
    print(f"tensorboard {info['id']} ready: {url}")
    return 0


def notebook_start(args) -> int:
    d = _client(args)
    info = d.start_notebook(work_dir=args.work_dir)
    info = d.wait_task_ready(info["id"], timeout=args.timeout)
    url = (f"{d.master}{info['proxy_url']}?dtpu_token={d.session.token}"
           f"&token={info.get('token', '')}")
    print(f"notebook {info['id']} ready: {url}")
    return 0


def workspace_create(args) -> int:
    info = _client(args).create_workspace(args.name)
    print(f"workspace {info['name']} created (owner {info['owner']})")
    return 0


def workspace_list(args) -> int:
    _table(
        _client(args).list_workspaces(),
        ["name", "experiments", "registered", "archived", "owner"],
    )
    return 0


def workspace_archive(args) -> int:
    _client(args).archive_workspace(args.name, archived=not args.undo)
    print(f"workspace {args.name} {'unarchived' if args.undo else 'archived'}")
    return 0


def workspace_delete(args) -> int:
    _client(args).delete_workspace(args.name)
    print(f"workspace {args.name} deleted")
    return 0


def workspace_assign(args) -> int:
    _client(args).assign_workspace_role(args.name, args.username, args.role)
    print(f"workspace {args.name}: {args.username} -> {args.role}")
    return 0


def events_cmd(args) -> int:
    """Stream the cluster event feed (reference `det` streams client)."""
    d = _client(args)
    try:
        for ev in d.events(
            since=args.since, follow=args.follow, types=args.type or None
        ):
            print(json.dumps(ev), flush=True)
    except KeyboardInterrupt:
        pass
    return 0


def shell_start(args) -> int:
    d = _client(args)
    info = d.start_shell(shell=args.shell)
    info = d.wait_task_ready(info["id"], timeout=args.timeout)
    print(f"shell {info['id']} ready")
    if getattr(args, "no_open", False):
        print(f"attach with: dtpu shell open {info['id']}")
        return 0
    args.id = info["id"]
    return shell_open(args)


def shell_open(args) -> int:
    """Attach the local terminal to the task PTY over the proxied websocket
    (reference: ``det shell open`` over an sshd tunnel)."""
    import json as _json
    import select as _select
    import shutil
    import termios
    import tty

    from determined_tpu.common import ws as wslib

    d = _client(args)
    ws = d.open_shell_ws(args.id)
    size = shutil.get_terminal_size()
    ws.send_text(_json.dumps({"type": "resize", "rows": size.lines, "cols": size.columns}))

    stdin_fd = sys.stdin.fileno()
    interactive = sys.stdin.isatty()
    saved = termios.tcgetattr(stdin_fd) if interactive else None
    if interactive:
        tty.setraw(stdin_fd)
    try:
        print("connected; exit the shell (or ctrl-d) to detach\r", flush=True)
        stdin_open = True
        while True:
            if ws.has_buffered_frame():
                r = [ws.sock]  # complete frame already read past select's view
            else:
                fds = [ws.sock] + ([stdin_fd] if stdin_open else [])
                r, _, _ = _select.select(fds, [], [])
            if ws.sock in r:
                op, data = ws.recv_message()
                if op == wslib.OP_CLOSE or ws.closed:
                    break
                if data:
                    os.write(sys.stdout.fileno(), data)
            if stdin_open and stdin_fd in r:
                data = os.read(stdin_fd, 65536)
                if not data:
                    # piped input exhausted: keep draining shell output
                    # until the remote side closes (the typical pipe ends
                    # with `exit`, which closes the PTY server-side)
                    stdin_open = False
                    continue
                ws.send_binary(data)
    except (ConnectionError, OSError):
        pass
    finally:
        if saved is not None:
            termios.tcsetattr(stdin_fd, termios.TCSADRAIN, saved)
        ws.close()
    print("\ndetached")
    return 0


def cmd_run(args) -> int:
    """Run an arbitrary command as a scheduler-placed task and stream its
    logs until it finishes (reference ``det cmd run``)."""
    argv = list(args.cmd or [])
    if argv and argv[0] == "--":
        argv = argv[1:]
    if not argv:
        print("usage: dtpu cmd run [--pool P] [--slots N] -- <command...>")
        return 2
    d = _client(args)
    info = d.run_command(
        argv if len(argv) > 1 else argv[0],
        resource_pool=args.pool,
        slots=args.slots,
    )
    tid = info["id"]
    print(f"command {tid} submitted to pool {info.get('resource_pool', 'default')}"
          + (" (queued)" if info.get("queued") else f" on {info.get('agent_id')}"))
    if args.detach:
        return 0
    import time as _time

    shown = 0
    while True:
        state = d.get_task(tid).get("state")
        logs = d.task_logs(tid)
        for rec in logs[shown:]:
            line = rec.get("line", "") if isinstance(rec, dict) else str(rec)
            print(line, flush=True)
        shown = len(logs)
        if state == "TERMINATED":
            return 0
        _time.sleep(0.5)


def token_create(args) -> int:
    info = _client(args).create_token(args.name, ttl_days=args.ttl_days,
                                      username=args.username)
    print(f"token {info['id']} ({info['name']}) for {info['username']} — "
          f"save the secret now, it is not shown again:")
    print(info["token"])
    return 0


def token_list(args) -> int:
    _table(_client(args).list_tokens(),
           ["id", "name", "username", "created_ms", "expires_ms"])
    return 0


def token_revoke(args) -> int:
    _client(args).revoke_token(args.id)
    print(f"revoked {args.id}")
    return 0


def task_list(args) -> int:
    _table(
        _client(args).list_tasks(),
        ["id", "type", "state", "ready", "queued", "resource_pool", "slots", "agent_id"],
    )
    return 0


def task_kill(args) -> int:
    _client(args).kill_task(args.id)
    print(f"killed {args.id}")
    return 0


# ---- devcluster (det deploy local analog) ----------------------------------


def _find_binary(name: str) -> str:
    import shutil

    env = os.environ.get(f"DTPU_{name.upper().replace('-', '_')}_BIN")
    if env and os.path.exists(env):
        return env
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    candidate = os.path.join(here, "native", "build", name)
    if os.path.exists(candidate):
        return candidate
    found = shutil.which(name)
    if found:
        return found
    raise SystemExit(
        f"{name} not found: build with `cmake -S native -B native/build && "
        f"cmake --build native/build` or set DTPU_{name.upper().replace('-', '_')}_BIN"
    )


def cluster_up(args) -> int:
    """Start a local master + N agents (reference: `det deploy local
    cluster-up`, minus docker — TPU VMs run processes directly)."""
    import signal as _signal
    import subprocess

    master_bin = _find_binary("dtpu-master")
    agent_bin = _find_binary("dtpu-agent")
    os.makedirs(args.state_dir, exist_ok=True)
    os.makedirs(args.checkpoint_dir, exist_ok=True)
    procs = [
        subprocess.Popen(
            [
                master_bin,
                "--host", "127.0.0.1",
                "--port", str(args.port),
                "--state-dir", args.state_dir,
                "--checkpoint-dir", args.checkpoint_dir,
                "--scheduler", args.scheduler,
            ]
        )
    ]
    import time as _time

    url = f"http://127.0.0.1:{args.port}"
    for i in range(args.agents):
        procs.append(
            subprocess.Popen(
                [
                    agent_bin,
                    "--master-host", "127.0.0.1",
                    "--master-port", str(args.port),
                    "--id", f"agent-{i}",
                    "--slots", str(args.slots),
                ]
            )
        )
    print(f"devcluster up: master {url}, {args.agents} agent(s) x {args.slots} slots")
    print("Ctrl-C to tear down")
    try:
        while all(p.poll() is None for p in procs):
            _time.sleep(1)
        print("a devcluster process exited; tearing down", file=sys.stderr)
        return 1
    except KeyboardInterrupt:
        return 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(_signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=5)
            except Exception:  # noqa: BLE001
                p.kill()


# ---- serve (online inference replica; docs/serving.md) ---------------------


class _ServeSignalFlag:
    """Signal-handler-safe drain flag: a plain attribute write holds no
    lock (the PR-7 signal-handler-unsafe rule; same pattern as
    ``experiment/local.py _PreemptFlag``).  The serve main loop polls it
    and runs the actual drain — which touches Events — on the main
    thread, never in handler context."""

    __slots__ = ("_flag",)

    def __init__(self) -> None:
        self._flag = False

    def set(self) -> None:
        self._flag = True

    def is_set(self) -> bool:
        return self._flag


def serve_cmd(args) -> int:
    """Run one online-serving replica from a trial checkpoint.

    Loads the checkpoint (``train.load_trial_from_checkpoint``), compiles
    the KV-cache prefill/decode steps, and serves ``POST /v1/generate``
    (+ ``/healthz``, ``/stats``).  With ``--master`` the replica registers
    under ``/api/v1/serving`` and heartbeats until shutdown.  SIGTERM or
    SIGINT drains: new requests are rejected (503), queued + in-flight
    requests finish, and the process exits 75 (EX_TEMPFAIL) so a
    supervisor knows the stop was orderly, not a crash.

    ``--model name[@version|@latest]`` serves a registry version instead
    of a raw path: the checkpoint is resolved through the master
    (``docs/registry.md``), the replica's listing label becomes
    ``name@vN``, and the resolved version rides registration — which is
    also what lets a rolling deploy (``dtpu model deploy``) find and
    drain replicas on older versions.  A master-requested drain exits 75
    exactly like a signal drain.
    """
    import signal as _signal
    import time as _time

    from determined_tpu.experiment import PREEMPTED_EXIT_CODE
    from determined_tpu.serve import ServeConfig, ServeEngine, ServeWorker

    try:
        serve_cfg = ServeConfig(
            block_size=args.block_size,
            num_blocks=args.num_blocks,
            max_batch=args.max_batch,
            max_prompt_len=args.max_prompt_len,
            max_new_tokens=args.max_new_tokens,
            queue_depth=args.queue_depth,
            prefix_cache=args.prefix_cache,
            decode_chunk_blocks=args.decode_chunk_blocks,
            host=args.host,
            port=args.port,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    session = None
    if args.master or os.environ.get("DTPU_MASTER"):
        session = _client(args).session
    checkpoint = args.checkpoint
    model_name, model_version = "", 0
    if args.model:
        from determined_tpu.experiment import registry as registry_mod

        if checkpoint:
            print("error: pass a checkpoint path OR --model, not both",
                  file=sys.stderr)
            return 2
        if session is None:
            print("error: --model resolves through the master "
                  "(pass -m/--master or set DTPU_MASTER)", file=sys.stderr)
            return 2
        try:
            ver = registry_mod.resolve_version(session, args.model)
        except Exception as e:  # noqa: BLE001 - CLI boundary
            print(f"error: {e}", file=sys.stderr)
            return 2
        model_name = ver["model"]
        model_version = int(ver["version"])
        checkpoint = ver.get("storage_path") or ""
        if not checkpoint or not os.path.isdir(checkpoint):
            print(f"error: {model_name}@v{model_version} resolves to "
                  f"storage path {checkpoint!r}, which is not a directory "
                  "on this host (serve replicas load via shared storage)",
                  file=sys.stderr)
            return 2
        print(f"resolved {args.model} -> {model_name}@v{model_version} "
              f"({checkpoint})", flush=True)
    elif not checkpoint:
        print("error: pass a checkpoint path or --model name@version",
              file=sys.stderr)
        return 2
    print(f"loading checkpoint {checkpoint} ...", flush=True)
    engine = ServeEngine.from_checkpoint(checkpoint, serve_cfg)
    # listing label precedence: explicit --model-name, then the registry
    # ref (name@vN), then the trial class name for raw-path launches
    if model_name:
        label = f"{model_name}@v{model_version}"
    else:
        label = args.model_name or engine.model_label
    worker = ServeWorker(
        engine,
        host=serve_cfg.host,
        port=serve_cfg.port,
        session=session,
        model=args.model_name or label,
        checkpoint=checkpoint,
        model_name=model_name,
        model_version=model_version,
    )
    url = worker.start()
    # the parseable contract scripts/tests rely on: one line, stable prefix
    print(f"serving on {url}", flush=True)

    drain_flag = _ServeSignalFlag()
    prev = {}

    def _on_signal(signum, frame):  # noqa: ARG001 - signal handler shape
        drain_flag.set()  # plain write: safe at any bytecode boundary

    for sig in (_signal.SIGTERM, _signal.SIGINT):
        prev[sig] = _signal.signal(sig, _on_signal)
    try:
        while not drain_flag.is_set() and not worker.master_drain_requested():
            _time.sleep(0.2)
        if worker.master_drain_requested() and not drain_flag.is_set():
            target = worker.master_drain_info.get("target") or "?"
            print(f"deploy drain requested by master (target {target})",
                  flush=True)
        print("drain requested: rejecting new requests, finishing in-flight",
              flush=True)
        worker.request_drain()
        clean = worker.wait_drained(timeout=serve_cfg.drain_grace_s)
        worker.shutdown()
        print(f"drained ({'clean' if clean else 'grace expired'}); exiting",
              flush=True)
        return PREEMPTED_EXIT_CODE
    finally:
        for sig, handler in prev.items():
            _signal.signal(sig, handler)


# ---- lint ------------------------------------------------------------------


def lint_cmd(args) -> int:
    """Static preflight analysis of trial code — no master required.

    Targets are .py files, directories (recursive), or
    ``pkg.module:TrialClass`` entrypoints.  ``--config`` additionally
    preflights an experiment YAML: parse-time validation plus the
    cross-field pipeline checks (schedule vs mesh pipe axis, n_layers
    divisibility into pipe x virtual_stages chunks, batch vs
    pipe_microbatches) that otherwise surface at trainer setup or the
    first step.  Exit status: 0 clean, 1 on error-severity findings (any
    finding with ``--strict``) or config problems, 2 on usage /
    unloadable target.
    """
    from determined_tpu import lint as lint_mod

    sys.path.insert(0, os.getcwd())
    if not args.target and not args.config and not args.native:
        print("error: nothing to lint (pass targets, --config, and/or --native)",
              file=sys.stderr)
        return 2
    config_problems = []
    for cfg_path in args.config or []:
        import yaml

        from determined_tpu.config.experiment import (
            ExperimentConfig,
            InvalidExperimentConfig,
            preflight_experiment_config,
        )

        try:
            with open(cfg_path, encoding="utf-8") as f:
                raw = yaml.safe_load(f) or {}
        except (OSError, yaml.YAMLError) as e:
            print(f"error: cannot read config {cfg_path}: {e}", file=sys.stderr)
            return 2
        try:
            cfg = ExperimentConfig.parse(raw)
        except InvalidExperimentConfig as e:
            config_problems.append(f"{cfg_path}: {e}")
            continue
        config_problems.extend(
            f"{cfg_path}: {p}" for p in preflight_experiment_config(cfg)
        )
    diags = []
    # path targets lint together as ONE program: the concurrency pass
    # builds a single cross-module lock graph spanning every target, so a
    # script taking package locks in the wrong order still forms a cycle
    path_targets = []
    for target in args.target:
        try:
            if os.path.exists(target):
                path_targets.append(target)
            elif ":" in target or "." in target:
                diags.extend(
                    lint_mod.analyze_entrypoint(
                        target, rules=args.rule or None, disabled=args.suppress or None
                    )
                )
            else:
                print(f"error: no such file, directory, or module: {target}",
                      file=sys.stderr)
                return 2
        except Exception as e:  # noqa: BLE001 - the entrypoint import runs
            # arbitrary user module code; ANY failure there is "target
            # unloadable" (exit 2), never "findings present" (exit 1)
            print(f"error: cannot lint {target}: {e}", file=sys.stderr)
            return 2
    if path_targets:
        try:
            diags.extend(
                lint_mod.analyze_paths(
                    path_targets, rules=args.rule or None,
                    disabled=args.suppress or None,
                    exclude=args.exclude or None,
                )
            )
        except Exception as e:  # noqa: BLE001 - unreadable file, bad rule id
            print(f"error: cannot lint {' '.join(path_targets)}: {e}",
                  file=sys.stderr)
            return 2
    if args.native:
        # control-plane contract pass: cross-reference the native
        # master/agent sources against the Python bindings, docs, and the
        # test suite's fake masters (docs/lint.md "Control-plane contract")
        from determined_tpu.lint.rules import build_rules

        root = None
        for cand in path_targets or [os.getcwd()]:
            root = lint_mod.find_native_root(os.path.abspath(cand))
            if root:
                break
        if not root:
            print("error: --native: no native/master/master.cpp above the "
                  "lint target (run from the repo)", file=sys.stderr)
            return 2
        try:
            diags.extend(
                lint_mod.lint_native(
                    root,
                    build_rules(args.rule or None, args.suppress or None),
                )
            )
        except Exception as e:  # noqa: BLE001 - unreadable source, bad rule id
            print(f"error: cannot run native pass over {root}: {e}",
                  file=sys.stderr)
            return 2
        diags.sort(key=lambda d: (d.file, d.line, d.col, d.rule))
    if args.json:
        payload = lint_mod.to_json_payload(diags)
        if args.config:
            payload["config_findings"] = config_problems
        _print_json(payload)
    else:
        for p in config_problems:
            print(f"config error: {p}")
        for d in diags:
            print(d.format())
        lint_errors = sum(1 for d in diags if d.severity == lint_mod.ERROR)
        errors = lint_errors + len(config_problems)
        warnings = len(diags) - lint_errors
        total = len(diags) + len(config_problems)
        print(
            f"{total} finding(s): {errors} error(s), {warnings} warning(s)"
            if total
            else "clean: no findings"
        )
    failing = [
        d for d in diags if d.severity == lint_mod.ERROR or args.strict
    ]
    return 1 if failing or config_problems else 0


# ---- search preview + local run -------------------------------------------


def preview_search(args) -> int:
    import yaml

    from determined_tpu.config.experiment import ExperimentConfig
    from determined_tpu.searcher import simulate

    with open(args.config) as f:
        raw = yaml.safe_load(f)
        cfg = ExperimentConfig.parse(raw)

    if getattr(args, "native", False):
        # drive the MASTER's C++ searcher (the parity twin of the Python
        # simulate below; see tests/test_searcher_parity.py)
        import subprocess
        import tempfile

        master_bin = _find_binary("dtpu-master")
        with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
            json.dump(raw, f)
            cfg_path = f.name
        try:
            sim = subprocess.run(
                [master_bin, "--simulate", cfg_path],
                capture_output=True,
                text=True,
                timeout=120,
            )
        finally:
            os.unlink(cfg_path)
        if sim.returncode != 0:
            print(sim.stderr, file=sys.stderr)
            return 1
        native = json.loads(sim.stdout)
        out = {
            "trials_created": native["trials_created"],
            "total_units": native["total_units"],
            "trial_units": native["trial_units"],
        }
    else:
        # synthetic smooth trial: improves with budget, hp-independent
        out = simulate(cfg, lambda hp, step: 1.0 / (1 + step), seed=0)
    smaller = cfg.searcher.smaller_is_better
    print(f"searcher: {cfg.searcher.name} (metric {cfg.searcher.metric}, "
          f"{'min' if smaller else 'max'})")
    print(f"trials created:   {out['trials_created']}")
    print(f"total units:      {out['total_units']}")
    units = sorted(out["trial_units"].values())
    print(f"units per trial:  min {units[0]}, median {units[len(units)//2]}, "
          f"max {units[-1]}")
    return 0


def exp_run(args) -> int:
    """Drive a search from this process.

    Default: the in-process ``LocalExperiment`` over ``jax.devices()``
    (exactly ``dtpu run-local``).  ``--cluster``: the search loop still
    runs HERE (journaled under ``--checkpoint-dir``), but every trial the
    searcher creates is submitted to the master, which gang-fits its slots
    across agents and launches one ``run_trial`` process per rank with
    ``jax.distributed`` rendezvous env (docs/cluster.md).
    """
    import yaml

    from determined_tpu.config.experiment import ExperimentConfig
    from determined_tpu.experiment import PREEMPTED_EXIT_CODE

    with open(args.config) as f:
        cfg = ExperimentConfig.parse(yaml.safe_load(f))
    entrypoint = getattr(args, "entrypoint", None) or cfg.entrypoint
    if not entrypoint:
        print(
            "error: no entrypoint (pass pkg.module:TrialClass or set "
            "`entrypoint:` in the config)",
            file=sys.stderr,
        )
        return 2
    if getattr(args, "cluster", False):
        from determined_tpu.experiment import ClusterExperiment

        exp = ClusterExperiment(
            cfg,
            entrypoint,
            session=_client(args).session,
            checkpoint_dir=args.checkpoint_dir,
        )
        summary = exp.run()
    else:
        from determined_tpu.experiment import LocalExperiment

        module_name, _, class_name = entrypoint.partition(":")
        sys.path.insert(0, os.getcwd())
        trial_cls = getattr(importlib.import_module(module_name), class_name)
        lexp = LocalExperiment(cfg, trial_cls, checkpoint_dir=args.checkpoint_dir)
        summary = lexp.run()
    _print_json(summary)
    if summary.get("status") == "preempted":
        # EX_TEMPFAIL: the search drained to checkpoints (local) or
        # detached from its running gangs (cluster); rerun with
        # `dtpu experiment resume <checkpoint_dir>` to finish it
        return PREEMPTED_EXIT_CODE
    return 0


# back-compat alias: `dtpu run-local` predates `dtpu experiment run`
run_local = exp_run


# ---- parser ----------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dtpu", description="determined-tpu CLI")
    p.add_argument("-m", "--master", help="master url (default $DTPU_MASTER)")
    p.add_argument(
        "--cert",
        help="CA bundle for an https master (default $DTPU_MASTER_CERT)",
    )
    p.add_argument("-u", "--user", help="username (default: cached or 'determined')")
    sub = p.add_subparsers(dest="noun", required=True)

    lg = sub.add_parser("login")
    lg.add_argument("-p", "--password")
    lg.set_defaults(fn=do_login)
    sub.add_parser("whoami").set_defaults(fn=do_whoami)

    user = sub.add_parser("user").add_subparsers(dest="verb", required=True)
    uc = user.add_parser("create")
    uc.add_argument("username")
    uc.add_argument("-p", "--password")
    uc.add_argument("--admin", action="store_true")
    uc.add_argument("--role", choices=["admin", "user", "viewer"])
    uc.set_defaults(fn=user_create)
    user.add_parser("list").set_defaults(fn=user_list)

    exp = sub.add_parser("experiment", aliases=["e"]).add_subparsers(
        dest="verb", required=True
    )
    c = exp.add_parser("create")
    c.add_argument("config")
    c.add_argument(
        "context_dir",
        nargs="?",
        help="model-code directory shipped to the cluster (.detignore honored)",
    )
    c.add_argument("-f", "--follow", action="store_true")
    c.add_argument("--template", help="master-stored config template to merge under")
    c.set_defaults(fn=exp_create)
    el = exp.add_parser("list")
    el.add_argument("--workspace")
    el.add_argument("--project")
    el.set_defaults(fn=exp_list)
    for verb in ("fork", "continue"):
        fk = exp.add_parser(verb)
        fk.add_argument("id", type=int)
        fk.add_argument("--config-overrides", help="yaml file merged over the source config")
        fk.add_argument("-f", "--follow", action="store_true")
        fk.set_defaults(fn=exp_fork, verb=verb)
    d = exp.add_parser("describe")
    d.add_argument("id", type=int)
    d.set_defaults(fn=exp_describe)
    for verb in ("pause", "activate", "cancel", "kill"):
        v = exp.add_parser(verb)
        v.add_argument("id", type=int)
        v.set_defaults(fn=exp_signal, verb=verb)
    dl = exp.add_parser("delete")
    dl.add_argument("id", type=int)
    dl.set_defaults(fn=exp_delete)
    rn = exp.add_parser(
        "run",
        help="drive a search from this process: in-process by default, "
        "--cluster dispatches trials through the master (docs/cluster.md)",
    )
    rn.add_argument("config")
    rn.add_argument(
        "entrypoint",
        nargs="?",
        help="pkg.module:TrialClass (default: `entrypoint:` in the config)",
    )
    rn.add_argument(
        "--cluster",
        action="store_true",
        help="submit searcher-created trials to the master for gang "
        "dispatch across agents instead of running them in-process",
    )
    rn.add_argument(
        "--checkpoint-dir",
        default=None,
        help="driver directory (journal + traces; default: ./local_… or "
        "./cluster_experiment_driver)",
    )
    rn.set_defaults(fn=exp_run)
    st = exp.add_parser(
        "status",
        help="journal-backed status of a LOCAL experiment directory",
    )
    st.add_argument("checkpoint_dir")
    st.add_argument("--json", action="store_true", help="machine-readable output")
    st.set_defaults(fn=exp_status_local)
    rs = exp.add_parser(
        "resume",
        help="resume a crashed/preempted LOCAL experiment from its journal",
    )
    rs.add_argument("checkpoint_dir")
    rs.add_argument(
        "--entrypoint",
        help="pkg.module:TrialClass (default: recorded in the journal)",
    )
    rs.add_argument("--serial", action="store_true", help="force the sequential loop")
    rs.set_defaults(fn=exp_resume_local)
    pf = exp.add_parser(
        "profile",
        help="goodput ledger + phase breakdown from a LOCAL experiment's traces",
    )
    pf.add_argument("checkpoint_dir")
    pf.add_argument("--json", action="store_true", help="machine-readable output")
    pf.add_argument(
        "--xplane",
        help="directory holding a sampled jax.profiler window "
        "(default: <dir>/traces/xplane)",
    )
    pf.set_defaults(fn=exp_profile_local)

    trial = sub.add_parser("trial", aliases=["t"]).add_subparsers(
        dest="verb", required=True
    )
    d = trial.add_parser("describe")
    d.add_argument("id", type=int)
    d.set_defaults(fn=trial_describe)
    lg = trial.add_parser("logs")
    lg.add_argument("id", type=int)
    lg.add_argument("-f", "--follow", action="store_true")
    lg.set_defaults(fn=trial_logs)
    mt = trial.add_parser("metrics")
    mt.add_argument("id", type=int)
    mt.add_argument("--group")
    mt.set_defaults(fn=trial_metrics)

    srch = sub.add_parser("searcher").add_subparsers(dest="verb", required=True)
    sim = srch.add_parser(
        "simulate",
        help="replay search methods against a learning-curve model "
        "(trial-free, deterministic; docs/searchers.md)",
    )
    sim.add_argument("-c", "--config", help="experiment config yaml "
                     "(default: a built-in lr search space)")
    sim.add_argument(
        "--methods",
        default="random,asha,hyperband,pbt",
        help="comma-separated method names to compare",
    )
    sim.add_argument("--seed", type=int, default=0)
    sim.add_argument("--period", type=int, default=0,
                     help="validation period in budget units (0 = per-method default)")
    sim.add_argument(
        "--journal",
        help="replay recorded curves from an experiment journal "
        "(file or checkpoint dir) instead of the synthetic model",
    )
    sim.add_argument("--json", action="store_true")
    sim.set_defaults(fn=searcher_simulate)

    agent = sub.add_parser("agent", aliases=["a"]).add_subparsers(
        dest="verb", required=True
    )
    agent.add_parser("list").set_defaults(fn=agent_list)

    pool = sub.add_parser("pool").add_subparsers(dest="verb", required=True)
    pool.add_parser("list").set_defaults(fn=pool_list)

    ckpt = sub.add_parser("checkpoint", aliases=["c"]).add_subparsers(
        dest="verb", required=True
    )
    ckpt.add_parser("list").set_defaults(fn=checkpoint_list)
    cd = ckpt.add_parser("download")
    cd.add_argument("uuid")
    cd.add_argument("--output", help="target directory (default: temp dir)")
    cd.set_defaults(fn=checkpoint_download)

    model = sub.add_parser(
        "model", help="model registry: versioned checkpoints promoted from "
        "trials, served and rolled onto the fleet (docs/registry.md)"
    ).add_subparsers(dest="verb", required=True)
    mc = model.add_parser("create")
    mc.add_argument("name")
    mc.add_argument("--description")
    mc.set_defaults(fn=model_create)
    model.add_parser("list").set_defaults(fn=model_list)
    ms = model.add_parser("show", help="model + every version with lineage")
    ms.add_argument("name")
    ms.add_argument("--json", action="store_true")
    ms.set_defaults(fn=model_show)
    mg = model.add_parser(
        "register", help="register a checkpoint as the model's next version"
    )
    mg.add_argument("name")
    mg.add_argument("checkpoint_uuid")
    mg.add_argument("--storage-path",
                    help="checkpoint directory (required when the master "
                         "does not track this checkpoint)")
    mg.add_argument("--trial-id", type=int, help="source trial lineage")
    mg.add_argument("--experiment-id", type=int, help="source experiment lineage")
    mg.add_argument("--metric", action="append", metavar="KEY=VALUE",
                    help="metrics snapshot entry (repeatable)")
    mg.add_argument("--label", action="append", help="version label (repeatable)")
    mg.add_argument("--version", type=int,
                    help="pin an explicit version number (409 if taken)")
    mg.set_defaults(fn=model_register)
    mp = model.add_parser(
        "promote", help="promote a trial's latest checkpoint to the next "
        "version (the master resolves lineage + metrics)"
    )
    mp.add_argument("name")
    mp.add_argument("trial_id", type=int)
    mp.set_defaults(fn=model_promote)
    mpl = model.add_parser("pull", help="materialize a version's checkpoint locally")
    mpl.add_argument("ref", metavar="NAME[@VERSION]")
    mpl.add_argument("--output", help="target directory (default: NAME-vN)")
    mpl.set_defaults(fn=model_pull)
    md = model.add_parser(
        "deploy", help="rolling-deploy a version onto the serving fleet "
        "(drain one replica at a time; supervisors relaunch on the target)"
    )
    md.add_argument("ref", metavar="NAME[@VERSION]")
    md.add_argument("--no-wait", dest="wait", action="store_false",
                    help="start the roll and return immediately")
    md.add_argument("--timeout", type=float, default=600.0,
                    help="seconds to wait for the roll to finish")
    md.add_argument("--canary", type=float, metavar="FRACTION",
                    help="roll this fraction of the fleet first and bake "
                         "it against the pre-roll error-rate/latency "
                         "baseline before finishing the roll")
    md.add_argument("--bake-seconds", type=float, default=30.0,
                    help="canary bake window (default: 30)")
    md.add_argument("--canary-min-requests", type=int, default=1,
                    help="minimum canary-cohort requests before the bake "
                         "verdict counts (default: 1)")
    md.add_argument("--rollback-on-regression", action="store_true",
                    help="on a canary regression, roll the cohort back to "
                         "the prior version instead of holding")
    md.set_defaults(fn=model_deploy, wait=True)

    fleet = sub.add_parser(
        "fleet", help="supervised serving fleet: the master relaunches "
        "replicas that die to hold the declared target (docs/serving.md)"
    ).add_subparsers(dest="verb", required=True)
    fs = fleet.add_parser(
        "set", help="declare the fleet spec (model version + replica count)"
    )
    fs.add_argument("ref", metavar="NAME[@VERSION]")
    fs.add_argument("--target", type=int, required=True,
                    help="replica count the supervisor holds")
    fs.add_argument("--pool", help="resource pool for replica tasks")
    fs.add_argument("--slots", type=int, help="slots per replica task")
    fs.add_argument("--env", action="append", metavar="KEY=VALUE",
                    help="environment override for replica tasks (repeatable)")
    fs.set_defaults(fn=fleet_set)
    fst = fleet.add_parser("status", help="fleet spec + per-slot health")
    fst.add_argument("--json", action="store_true")
    fst.set_defaults(fn=fleet_status)
    mr = model.add_parser("register-version")
    mr.add_argument("name")
    mr.add_argument("checkpoint_uuid")
    mr.set_defaults(fn=model_register_version)

    master = sub.add_parser("master").add_subparsers(dest="verb", required=True)
    master.add_parser("info").set_defaults(fn=master_info)

    tpl = sub.add_parser("template").add_subparsers(dest="verb", required=True)
    tset = tpl.add_parser("set")
    tset.add_argument("name")
    tset.add_argument("config")
    tset.set_defaults(fn=template_set)
    tpl.add_parser("list").set_defaults(fn=template_list)
    td = tpl.add_parser("describe")
    td.add_argument("name")
    td.set_defaults(fn=template_describe)
    tr = tpl.add_parser("remove")
    tr.add_argument("name")
    tr.set_defaults(fn=template_remove)

    tb = sub.add_parser("tensorboard").add_subparsers(dest="verb", required=True)
    ts = tb.add_parser("start")
    ts.add_argument("experiment_ids", nargs="*", type=int)
    ts.add_argument("--timeout", type=float, default=60.0)
    ts.set_defaults(fn=tensorboard_start)

    nb = sub.add_parser("notebook").add_subparsers(dest="verb", required=True)
    ns = nb.add_parser("start")
    ns.add_argument("--work-dir")
    ns.add_argument("--timeout", type=float, default=150.0)
    ns.set_defaults(fn=notebook_start)

    ws = sub.add_parser("workspace", aliases=["w"]).add_subparsers(
        dest="verb", required=True
    )
    wc = ws.add_parser("create")
    wc.add_argument("name")
    wc.set_defaults(fn=workspace_create)
    ws.add_parser("list").set_defaults(fn=workspace_list)
    wa = ws.add_parser("archive")
    wa.add_argument("name")
    wa.add_argument("--undo", action="store_true")
    wa.set_defaults(fn=workspace_archive)
    wd = ws.add_parser("delete")
    wd.add_argument("name")
    wd.set_defaults(fn=workspace_delete)
    wr = ws.add_parser("assign")
    wr.add_argument("name")
    wr.add_argument("username")
    wr.add_argument("role", choices=["viewer", "user", "admin", "none"])
    wr.set_defaults(fn=workspace_assign)

    ev = sub.add_parser("events")
    ev.add_argument("-f", "--follow", action="store_true")
    ev.add_argument("--since", type=int, default=0)
    ev.add_argument("--type", action="append", help="filter by event type (repeatable)")
    ev.set_defaults(fn=events_cmd)

    sh = sub.add_parser("shell").add_subparsers(dest="verb", required=True)
    ss = sh.add_parser("start")
    ss.add_argument("--shell", default="/bin/sh")
    ss.add_argument("--timeout", type=float, default=60.0)
    ss.add_argument("--no-open", action="store_true",
                    help="start only; do not attach a terminal")
    ss.set_defaults(fn=shell_start)
    so = sh.add_parser("open")
    so.add_argument("id")
    so.set_defaults(fn=shell_open)

    task = sub.add_parser("task").add_subparsers(dest="verb", required=True)
    task.add_parser("list").set_defaults(fn=task_list)
    tk = task.add_parser("kill")
    tk.add_argument("id")
    tk.set_defaults(fn=task_kill)

    tok = sub.add_parser("token").add_subparsers(dest="verb", required=True)
    tc = tok.add_parser("create")
    tc.add_argument("name")
    tc.add_argument("--ttl-days", type=int, default=30)
    tc.add_argument("--username", default=None, help="admin: issue for another user")
    tc.set_defaults(fn=token_create)
    tok.add_parser("list").set_defaults(fn=token_list)
    tr = tok.add_parser("revoke")
    tr.add_argument("id")
    tr.set_defaults(fn=token_revoke)

    cmd = sub.add_parser("cmd").add_subparsers(dest="verb", required=True)
    cr = cmd.add_parser("run")
    cr.add_argument("--pool", default=None, help="resource pool (incl. k8s/slurm pools)")
    cr.add_argument("--slots", type=int, default=0)
    cr.add_argument("--detach", action="store_true")
    cr.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to run (prefix with --)")
    cr.set_defaults(fn=cmd_run)

    cl = sub.add_parser("cluster").add_subparsers(dest="verb", required=True)
    cu = cl.add_parser("up")
    cu.add_argument("--port", type=int, default=8080)
    cu.add_argument("--agents", type=int, default=1)
    cu.add_argument("--slots", type=int, default=4)
    cu.add_argument("--scheduler", default="priority",
                    choices=["priority", "fair_share"])
    cu.add_argument("--state-dir", default="/tmp/dtpu-master")
    cu.add_argument("--checkpoint-dir", default="/tmp/dtpu-checkpoints")
    cu.set_defaults(fn=cluster_up)

    sv = sub.add_parser(
        "serve",
        help="run an online-serving replica from a trial checkpoint "
        "(docs/serving.md)",
    )
    sv.add_argument("checkpoint", nargs="?", default=None,
                    help="trial checkpoint directory to serve "
                         "(or use --model to resolve one via the registry)")
    sv.add_argument("--model", default=None, metavar="NAME[@VERSION]",
                    help="serve a registry model version resolved through "
                         "the master, e.g. lm@latest or lm@v3 "
                         "(docs/registry.md)")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument(
        "--port", type=int, default=0,
        help="HTTP port (default 0: OS-assigned, printed at startup)",
    )
    sv.add_argument("--block-size", type=int, default=16,
                    help="tokens per KV-cache block")
    sv.add_argument("--num-blocks", type=int, default=256,
                    help="KV-cache pool size in blocks")
    sv.add_argument("--max-batch", type=int, default=8,
                    help="decode lanes (max sequences in flight)")
    sv.add_argument("--max-prompt-len", type=int, default=128)
    sv.add_argument("--max-new-tokens", type=int, default=64)
    sv.add_argument("--queue-depth", type=int, default=16,
                    help="admission queue depth (full -> 429)")
    sv.add_argument("--prefix-cache", dest="prefix_cache",
                    action="store_true", default=True,
                    help="share KV blocks across requests with a common "
                         "prompt prefix (default on; docs/serving.md)")
    sv.add_argument("--no-prefix-cache", dest="prefix_cache",
                    action="store_false",
                    help="disable prefix sharing: every request prefills "
                         "private blocks")
    sv.add_argument("--decode-chunk-blocks", type=int, default=1,
                    help="lazy decode: gather the block table this many "
                         "columns per attention pass, skipping columns "
                         "past the longest live sequence (0 = legacy "
                         "full-table gather; must divide the table width)")
    sv.add_argument("--model-name", default=None,
                    help="label shown in the master's replica listing")
    sv.set_defaults(fn=serve_cmd)

    ln = sub.add_parser(
        "lint",
        help="static preflight analysis of trial code (docs/lint.md)",
    )
    ln.add_argument(
        "target",
        nargs="*",
        help=".py file, directory, or pkg.module:TrialClass entrypoint",
    )
    ln.add_argument(
        "--config", action="append", metavar="YAML",
        help="experiment config to preflight (repeatable): parse "
             "validation + cross-field pipeline-schedule checks "
             "(n_layers vs pipe x virtual_stages, batch vs "
             "pipe_microbatches) before any device work",
    )
    ln.add_argument("--json", action="store_true", help="machine-readable output")
    ln.add_argument(
        "--strict", action="store_true",
        help="exit non-zero on ANY finding (default: errors only)",
    )
    ln.add_argument(
        "--rule", action="append",
        help="restrict to specific rule ids (repeatable)",
    )
    ln.add_argument(
        "--suppress", action="append",
        help="disable specific rule ids (repeatable)",
    )
    ln.add_argument(
        "--native", action="store_true",
        help="also run the control-plane contract pass: cross-reference "
             "native/master + native/agent (routes, WAL record types, "
             "/metrics names, wire payloads) against api/spec.py, API.md, "
             "docs/operations.md, the devcluster fuzz fixtures, and the "
             "test suite's fake masters",
    )
    ln.add_argument(
        "--exclude", action="append", metavar="GLOB",
        help="skip files/dirs matching this glob in dir-mode targets "
             "(repeatable; matched against basenames and target-relative "
             "paths — excluded directories are pruned, so a live "
             "experiment's checkpoint/journal/trace artifacts are never "
             "walked)",
    )
    ln.set_defaults(fn=lint_cmd)

    ps = sub.add_parser("preview-search")
    ps.add_argument("config")
    ps.add_argument("--native", action="store_true",
                    help="simulate with the master's C++ searcher")
    ps.set_defaults(fn=preview_search)

    rl = sub.add_parser("run-local")
    rl.add_argument("config")
    rl.add_argument("entrypoint", help="pkg.module:TrialClass")
    rl.add_argument("--checkpoint-dir", default=None)
    rl.set_defaults(fn=run_local)

    from determined_tpu.cli import deploy

    deploy.register(sub)

    return p


def main(argv: Optional[List[str]] = None) -> int:
    from determined_tpu.api.session import APIError

    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except KeyboardInterrupt:
        return 130
    except APIError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
