"""dtpu CLI: the ``det`` command-line equivalent.

Reference: ``harness/determined/cli/`` (declarative argparse per noun:
experiment/trial/agent/checkpoint/master).  Talks to the master REST API
via the same Session the harness uses; ``run-local`` drives the in-process
LocalExperiment runner for masterless single-host searches.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional


def _session(args):
    from determined_tpu.api.session import Session

    url = args.master or os.environ.get("DTPU_MASTER", "http://127.0.0.1:8080")
    return Session(url)


def _print_json(obj: Any) -> None:
    print(json.dumps(obj, indent=2, sort_keys=True, default=str))


def _table(rows: List[Dict[str, Any]], cols: List[str]) -> None:
    if not rows:
        print("(none)")
        return
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows)) for c in cols}
    print("  ".join(c.upper().ljust(widths[c]) for c in cols))
    for r in rows:
        print("  ".join(str(r.get(c, "")).ljust(widths[c]) for c in cols))


# ---- experiment ------------------------------------------------------------


def exp_create(args) -> int:
    import yaml

    with open(args.config) as f:
        config = yaml.safe_load(f)
    # config validation before submit (reference validates cluster-side too)
    from determined_tpu.config.experiment import ExperimentConfig

    ExperimentConfig.parse(dict(config))
    body: Dict[str, Any] = {"config": config}
    if getattr(args, "context_dir", None):
        import base64

        from determined_tpu.common import build_context

        data = build_context(args.context_dir)
        body["context"] = base64.b64encode(data).decode("ascii")
        print(f"context: {args.context_dir} ({len(data)} bytes packed)")
    resp = _session(args).post("/api/v1/experiments", json=body)
    exp_id = resp.json()["id"]
    print(f"Created experiment {exp_id}")
    if args.follow:
        return exp_wait(args, exp_id)
    return 0


def exp_wait(args, exp_id: int) -> int:
    s = _session(args)
    last_state = None
    while True:
        exp = s.get(f"/api/v1/experiments/{exp_id}").json()
        if exp["state"] != last_state:
            print(f"state: {exp['state']} (progress {exp.get('progress', 0):.0%})")
            last_state = exp["state"]
        if exp["state"] in ("COMPLETED", "CANCELED", "ERROR"):
            return 0 if exp["state"] == "COMPLETED" else 1
        time.sleep(2)


def exp_list(args) -> int:
    exps = _session(args).get("/api/v1/experiments").json()
    _table(
        [
            {
                "id": e["id"],
                "name": e.get("name", ""),
                "state": e["state"],
                "progress": f"{e.get('progress', 0):.0%}",
                "trials": len(e.get("trials", [])),
            }
            for e in exps
        ],
        ["id", "name", "state", "progress", "trials"],
    )
    return 0


def exp_describe(args) -> int:
    _print_json(_session(args).get(f"/api/v1/experiments/{args.id}").json())
    return 0


def exp_signal(args) -> int:
    resp = _session(args).post(f"/api/v1/experiments/{args.id}/{args.verb}")
    print(f"experiment {args.id}: {resp.json()['state']}")
    return 0


# ---- trial -----------------------------------------------------------------


def trial_describe(args) -> int:
    _print_json(_session(args).get(f"/api/v1/trials/{args.id}").json())
    return 0


def trial_logs(args) -> int:
    s = _session(args)
    offset = 0
    while True:
        lines = s.get(f"/api/v1/trials/{args.id}/logs", params={"offset": offset}).json()
        for line in lines:
            print(line)
        offset += len(lines)
        if not args.follow:
            return 0
        trial = s.get(f"/api/v1/trials/{args.id}").json()
        if trial["state"] not in ("PENDING", "RUNNING"):
            return 0
        time.sleep(1)


def trial_metrics(args) -> int:
    params = {"group": args.group} if args.group else None
    _print_json(
        _session(args).get(f"/api/v1/trials/{args.id}/metrics", params=params).json()
    )
    return 0


# ---- agents / checkpoints / master ----------------------------------------


def agent_list(args) -> int:
    _table(
        _session(args).get("/api/v1/agents").json(),
        ["id", "host", "slots", "used_slots"],
    )
    return 0


def checkpoint_list(args) -> int:
    cps = _session(args).get("/api/v1/checkpoints").json()
    _table(
        [
            {"uuid": c["uuid"], "trial_id": c.get("trial_id"),
             "steps": (c.get("metadata") or {}).get("steps_completed")}
            for c in cps
        ],
        ["uuid", "trial_id", "steps"],
    )
    return 0


def master_info(args) -> int:
    _print_json(_session(args).get("/api/v1/master").json())
    return 0


# ---- search preview + local run -------------------------------------------


def preview_search(args) -> int:
    import yaml

    from determined_tpu.config.experiment import ExperimentConfig
    from determined_tpu.searcher import simulate

    with open(args.config) as f:
        cfg = ExperimentConfig.parse(yaml.safe_load(f))

    # synthetic smooth trial: improves with budget, hp-independent
    out = simulate(cfg, lambda hp, step: 1.0 / (1 + step), seed=0)
    smaller = cfg.searcher.smaller_is_better
    print(f"searcher: {cfg.searcher.name} (metric {cfg.searcher.metric}, "
          f"{'min' if smaller else 'max'})")
    print(f"trials created:   {out['trials_created']}")
    print(f"total units:      {out['total_units']}")
    units = sorted(out["trial_units"].values())
    print(f"units per trial:  min {units[0]}, median {units[len(units)//2]}, "
          f"max {units[-1]}")
    return 0


def run_local(args) -> int:
    import yaml

    from determined_tpu.config.experiment import ExperimentConfig
    from determined_tpu.experiment import LocalExperiment

    with open(args.config) as f:
        cfg = ExperimentConfig.parse(yaml.safe_load(f))
    module_name, _, class_name = args.entrypoint.partition(":")
    sys.path.insert(0, os.getcwd())
    trial_cls = getattr(importlib.import_module(module_name), class_name)
    exp = LocalExperiment(cfg, trial_cls, checkpoint_dir=args.checkpoint_dir)
    summary = exp.run()
    _print_json(summary)
    return 0


# ---- parser ----------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dtpu", description="determined-tpu CLI")
    p.add_argument("-m", "--master", help="master url (default $DTPU_MASTER)")
    sub = p.add_subparsers(dest="noun", required=True)

    exp = sub.add_parser("experiment", aliases=["e"]).add_subparsers(
        dest="verb", required=True
    )
    c = exp.add_parser("create")
    c.add_argument("config")
    c.add_argument(
        "context_dir",
        nargs="?",
        help="model-code directory shipped to the cluster (.detignore honored)",
    )
    c.add_argument("-f", "--follow", action="store_true")
    c.set_defaults(fn=exp_create)
    exp.add_parser("list").set_defaults(fn=exp_list)
    d = exp.add_parser("describe")
    d.add_argument("id", type=int)
    d.set_defaults(fn=exp_describe)
    for verb in ("pause", "activate", "cancel", "kill"):
        v = exp.add_parser(verb)
        v.add_argument("id", type=int)
        v.set_defaults(fn=exp_signal, verb=verb)

    trial = sub.add_parser("trial", aliases=["t"]).add_subparsers(
        dest="verb", required=True
    )
    d = trial.add_parser("describe")
    d.add_argument("id", type=int)
    d.set_defaults(fn=trial_describe)
    lg = trial.add_parser("logs")
    lg.add_argument("id", type=int)
    lg.add_argument("-f", "--follow", action="store_true")
    lg.set_defaults(fn=trial_logs)
    mt = trial.add_parser("metrics")
    mt.add_argument("id", type=int)
    mt.add_argument("--group")
    mt.set_defaults(fn=trial_metrics)

    agent = sub.add_parser("agent", aliases=["a"]).add_subparsers(
        dest="verb", required=True
    )
    agent.add_parser("list").set_defaults(fn=agent_list)

    ckpt = sub.add_parser("checkpoint", aliases=["c"]).add_subparsers(
        dest="verb", required=True
    )
    ckpt.add_parser("list").set_defaults(fn=checkpoint_list)

    master = sub.add_parser("master").add_subparsers(dest="verb", required=True)
    master.add_parser("info").set_defaults(fn=master_info)

    ps = sub.add_parser("preview-search")
    ps.add_argument("config")
    ps.set_defaults(fn=preview_search)

    rl = sub.add_parser("run-local")
    rl.add_argument("config")
    rl.add_argument("entrypoint", help="pkg.module:TrialClass")
    rl.add_argument("--checkpoint-dir", default=None)
    rl.set_defaults(fn=run_local)

    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except KeyboardInterrupt:
        return 130


if __name__ == "__main__":
    sys.exit(main())
