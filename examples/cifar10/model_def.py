"""CIFAR-class ResNet trial (BASELINE.json's cifar10_pytorch workload,
rebuilt TPU-first; see determined_tpu/models/resnet.py)."""

from determined_tpu.models.resnet import CifarTrial


class Trial(CifarTrial):
    pass
