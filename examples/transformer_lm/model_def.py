"""Decoder-only transformer LM trial — the flagship model (the class the
reference trains via hf_trainer / deepspeed gpt_neox examples), with
DP/FSDP/TP/SP selected purely by `resources.mesh` in the yaml."""

from determined_tpu.models.transformer import LMTrial


class Trial(LMTrial):
    pass
