"""DDPM diffusion trial — the platform's diffusion example family
(reference: examples/diffusion/, a HF-diffusers fine-tune under Core API;
here an in-tree TPU-native UNet + DDPM, see
determined_tpu/models/diffusion.py).  Submit with:

    dtpu experiment create examples/diffusion/const.yaml examples/diffusion
"""

from determined_tpu.models.diffusion import DiffusionTrial


class Trial(DiffusionTrial):
    """Direct reuse of the in-tree DDPM trial; subclass to customize."""
