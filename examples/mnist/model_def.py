"""MNIST MLP/CNN trial — the platform's `mnist_pytorch` tutorial analog
(reference: examples/tutorials/mnist_pytorch/model_def.py, redesigned as a
JaxTrial).  Submit with any yaml in this directory:

    dtpu experiment create examples/mnist/const.yaml examples/mnist
"""

from determined_tpu.models.mnist import MnistTrial


class Trial(MnistTrial):
    """Direct reuse of the in-tree MNIST trial; subclass to customize."""
