"""HF Flax BERT sequence classification (hf_trainer_api analog)."""

from determined_tpu.models.hf_bert import BertClassifyTrial


class Trial(BertClassifyTrial):
    pass
