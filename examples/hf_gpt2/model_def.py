"""HF Flax GPT-2 causal-LM fine-tune (reference: examples/hf_trainer_api;
see determined_tpu/models/hf_gpt2.py).  Submit with:

    dtpu experiment create examples/hf_gpt2/const.yaml examples/hf_gpt2
"""

from determined_tpu.models.hf_gpt2 import GPT2FinetuneTrial


class Trial(GPT2FinetuneTrial):
    """Direct reuse of the in-tree GPT-2 trial; subclass to customize."""
